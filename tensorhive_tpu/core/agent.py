"""tpuhive-agent: push-based host membership + telemetry.

The reference (and PR 1-19 of this rebuild) is pull-only: MonitoringService
fans an SSH probe out to every configured host each 2 s tick — O(hosts)
round-trips, membership frozen at config time, and a silent host
indistinguishable from a slow one until the breaker trips. Following
JIRIAF's virtual-kubelet model (PAPERS.md), this agent inverts the
direction for hosts that run it: the host itself executes the SAME schema-v1
probe (monitors/probe.py) locally each heartbeat interval and POSTs the
document plus a monotonically-sequenced heartbeat to
``POST /api/agent/report`` (token-authed). The server side keeps a lease per
host (InfrastructureManager.agent_report/sweep_leases,
docs/ROBUSTNESS.md "Host membership & leases"); missed heartbeats walk
``live → suspect → unreachable → deregistered`` without a single SSH
round-trip.

Wire format (version 1)::

    {"v": 1,
     "hostname": "tpu-vm-3",
     "incarnation": "9f2c...",     # fresh per agent process: restarting the
                                   # agent resets the sequence space
     "seq": 42,                    # strictly monotonic within an incarnation
     "sent_ts": 1699999999.2,      # agent clock (informational only — the
                                   # server measures leases on ITS clock, so
                                   # agent clock skew cannot expire a lease)
     "probe": {...},               # one schema-v1 probe document
     "host": {"accelerator_type": ..., "chips": ..., ...}}  # optional
                                   # self-description for dynamic first join

The agent is dependency-free (stdlib urllib) so it can run on a bare TPU VM
from a single file. Everything nondeterministic is injectable — clock,
probe collection, transport — and a :class:`FaultPlan` from
``core/transport/fake.py`` can silence/duplicate/skew reports, which is how
membership churn becomes deterministic in CI (tools/agent_smoke.py).
"""
from __future__ import annotations

import argparse
import contextlib
import io
import json
import logging
import time
import urllib.error
import urllib.request
import uuid
from typing import Any, Callable, Dict, Optional, Tuple

from .monitors.probe import PYTHON_PROBE_SOURCE

log = logging.getLogger(__name__)

AGENT_WIRE_VERSION = 1


def collect_local_probe() -> Dict[str, Any]:
    """Run the inline python probe in-process and return the raw schema-v1
    document. In-process (exec + captured stdout) rather than a subprocess:
    the agent IS the python interpreter on the host, so a fork per heartbeat
    would only add latency and an OOM-kill surface."""
    buffer = io.StringIO()
    with contextlib.redirect_stdout(buffer):
        exec(compile(PYTHON_PROBE_SOURCE, "<tpuhive-probe>", "exec"), {})  # noqa: S102
    return json.loads(buffer.getvalue().strip().splitlines()[-1])


def _default_post(url: str, payload: bytes, token: str,
                  timeout_s: float) -> Tuple[int, Dict[str, Any]]:
    request = urllib.request.Request(
        url, data=payload, method="POST",
        headers={"Content-Type": "application/json",
                 "Authorization": f"Bearer {token}"})
    try:
        with urllib.request.urlopen(request, timeout=timeout_s) as response:
            body = response.read().decode("utf-8", errors="replace")
            return response.status, _safe_json(body)
    except urllib.error.HTTPError as exc:
        body = exc.read().decode("utf-8", errors="replace")
        return exc.code, _safe_json(body)


def _safe_json(body: str) -> Dict[str, Any]:
    try:
        doc = json.loads(body)
        return doc if isinstance(doc, dict) else {}
    except ValueError:
        return {}


class HostAgent:
    """One agent loop for one host. Sequence numbers are strictly monotonic
    within an ``incarnation``; a process restart mints a new incarnation, so
    the server's idempotence window resets cleanly on re-join."""

    def __init__(
        self,
        hostname: str,
        server_url: str,
        token: str,
        interval_s: float = 2.0,
        host_info: Optional[Dict[str, Any]] = None,
        collect: Optional[Callable[[], Dict[str, Any]]] = None,
        clock: Optional[Callable[[], float]] = None,
        post: Optional[Callable[..., Tuple[int, Dict[str, Any]]]] = None,
        fault_plan: Optional[Any] = None,
        incarnation: Optional[str] = None,
        timeout_s: float = 5.0,
    ) -> None:
        self.hostname = hostname
        self.server_url = server_url.rstrip("/")
        self.token = token
        self.interval_s = interval_s
        self.host_info = host_info or {}
        self._collect = collect or collect_local_probe
        self._clock = clock or time.time
        self._post = post or _default_post
        self._fault_plan = fault_plan
        self.incarnation = incarnation or uuid.uuid4().hex
        self.timeout_s = timeout_s
        self.seq = 0
        self.reports_sent = 0
        self.reports_suppressed = 0
        self._stop = False

    # ------------------------------------------------------------------
    def build_report(self) -> Dict[str, Any]:
        self.seq += 1
        sent_ts = self._clock()
        if self._fault_plan is not None:
            # clock_skew_s only shifts the agent's self-reported stamp: the
            # lease is measured on the SERVER clock, and the smoke/tests pin
            # that a skewed agent cannot expire (or immortalize) its lease
            sent_ts += getattr(self._fault_plan, "clock_skew_s", 0.0)
        report = {
            "v": AGENT_WIRE_VERSION,
            "hostname": self.hostname,
            "incarnation": self.incarnation,
            "seq": self.seq,
            "sent_ts": sent_ts,
            "probe": self._collect(),
        }
        if self.host_info:
            report["host"] = dict(self.host_info)
        return report

    def report_once(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Collect + send one report; returns (status, response) or None
        when the fault plan silenced this heartbeat. Duplicate-delivery
        faults send the SAME payload twice — the at-least-once case the
        server's sequence idempotence must absorb."""
        sends = 1
        if self._fault_plan is not None:
            event = self._fault_plan.agent_event()
            if event == "silence":
                self.reports_suppressed += 1
                return None
            if event == "duplicate":
                sends = 2
        payload = json.dumps(self.build_report()).encode()
        url = f"{self.server_url}/agent/report"
        outcome: Optional[Tuple[int, Dict[str, Any]]] = None
        for _ in range(sends):
            try:
                outcome = self._post(url, payload, self.token, self.timeout_s)
            except (urllib.error.URLError, OSError, TimeoutError) as exc:
                # server briefly away: keep heartbeating — the lease plane
                # is exactly the machinery that tolerates missed reports
                log.warning("agent report to %s failed: %s", url, exc)
                outcome = None
            else:
                self.reports_sent += 1
        return outcome

    # ------------------------------------------------------------------
    def stop(self) -> None:
        self._stop = True

    def run(self, max_reports: Optional[int] = None,
            sleep: Optional[Callable[[float], None]] = None) -> None:
        sleep = sleep or time.sleep
        sent = 0
        while not self._stop:
            self.report_once()
            sent += 1
            if max_reports is not None and sent >= max_reports:
                return
            sleep(self.interval_s)


def main(argv: Optional[list] = None) -> int:
    import socket

    parser = argparse.ArgumentParser(
        description="tpuhive host agent: push telemetry + heartbeat lease")
    parser.add_argument("--server", required=True,
                        help="API base URL, e.g. http://controller:1111/api")
    parser.add_argument("--token", required=True, help="shared agent token")
    parser.add_argument("--hostname", default=socket.gethostname())
    parser.add_argument("--interval-s", type=float, default=2.0)
    parser.add_argument("--accelerator-type", default="",
                        help="self-described accelerator type for dynamic join")
    parser.add_argument("--chips", type=int, default=0)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")
    host_info: Dict[str, Any] = {}
    if args.accelerator_type:
        host_info["accelerator_type"] = args.accelerator_type
    if args.chips:
        host_info["chips"] = args.chips
    agent = HostAgent(args.hostname, args.server, args.token,
                      interval_s=args.interval_s, host_info=host_info)
    log.info("tpuhive-agent reporting %s -> %s every %.1fs",
             args.hostname, args.server, args.interval_s)
    try:
        agent.run()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
