"""Remote process lifecycle: spawn / terminate / running / fetch_log.

Reference: tensorhive/core/task_nursery.py (315 LoC) builds GNU ``screen``
sessions named ``tensorhive_task_<id>`` over SSH: spawn returns the screen
PID (:50-96,167-190), terminate escalates SIGINT → screen quit → kill -9
(:132-147), ``running()`` greps ``screen -ls`` (:272-291), ``fetch_log``
tails ``~/TensorHiveLogs`` (:294-315).

TPU VMs don't ship screen, so this rebuild uses bare POSIX process groups:
``setsid`` makes the spawned wrapper a session+group leader whose PID is
written to a pidfile and adopted back after daemon restarts; signals go to
the whole group (``kill -- -PID``), so multi-process trainings die with
their wrapper. A task marker embedded in the wrapper's argv guards PID-reuse
during adoption (the analog of the reference's screen-session-name matching).
Output is redirected straight to a per-task logfile — equivalent to the
reference's ``tee --ignore-interrupts`` pipeline without the extra process.

All operations funnel through :class:`HostOps`, the injectable seam that the
fake cluster re-implements in-process (closing the reference's "not testable
without a live host" gap, task_nursery.py:34 "TODO Write tests").
"""
from __future__ import annotations

import enum
import logging
import shlex
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

from ..utils.exceptions import SpawnError, TransportError

if TYPE_CHECKING:  # import only for annotations; avoids transport<->nursery cycle
    from .transport.base import Transport

log = logging.getLogger(__name__)

RUN_DIR = "$HOME/.tpuhive/run"
LOG_DIR = "$HOME/.tpuhive/logs"
TASK_MARKER_PREFIX = "tpuhive_task_"


class Termination(str, enum.Enum):
    """Escalation ladder (reference task_nursery.py:250-269: gracefully=True
    → SIGINT, None → screen quit ≈ SIGTERM, False → kill -9)."""

    interrupt = "INT"
    terminate = "TERM"
    kill = "KILL"


class HostOps:
    """Process operations on one (host, user) channel, shell implementation.

    Subclassed by the fake backend; every public method is part of the seam.
    """

    def __init__(
        self,
        transport: "Transport",
        run_dir: str = RUN_DIR,
        log_dir: str = LOG_DIR,
    ) -> None:
        self.transport = transport
        self.run_dir = run_dir
        self.log_dir = log_dir

    @property
    def hostname(self) -> str:
        return self.transport.hostname

    # -- task lifecycle ----------------------------------------------------
    def spawn(self, command: str, task_id: int, timeout: Optional[float] = None) -> int:
        """Start ``command`` detached; returns the session-leader PID.

        The wrapper script: writes its PID, runs the command with stdout+err
        appended to the task log, exits with the command's status. The task
        marker rides in the wrapper's argv for adoption checks.
        """
        # trailing ':' bounds the id so task 1's marker never substring-matches
        # a recycled PID now running task 12
        marker = f"{TASK_MARKER_PREFIX}{task_id}:"
        pidfile = f"{self.run_dir}/task_{task_id}.pid"
        logfile = f"{self.log_dir}/task_{task_id}.log"
        # NOTE: command is embedded unquoted inside the wrapper's -c script so
        # user-supplied shell (pipes, &&) keeps working — same contract as the
        # reference, which passes the raw command line to screen's bash -c.
        wrapper = (
            f'echo $$ > "{pidfile}"\n'
            f"{command}\n"
            f"rc=$?\n"
            f"exit $rc # {marker}"
        )
        # `setsid --fork` (not `&`) does the detach: the parent returns
        # immediately while the child starts a fresh session with DEFAULT
        # signal dispositions — backgrounding with `&` in a non-interactive
        # shell would leave SIGINT/SIGQUIT at SIG_IGN in every descendant,
        # making graceful interrupt-termination impossible
        # `>` not `>>`: each spawn starts a fresh log (the reference gets the
        # same semantic from a fresh mktemp per spawn, task_nursery.py:90-96)
        script = (
            f'mkdir -p "{self.run_dir}" "{self.log_dir}" && rm -f "{pidfile}" && '
            f'setsid --fork bash -c {shlex.quote(wrapper)} > "{logfile}" 2>&1 < /dev/null; '
            f'for _ in $(seq 1 100); do [ -s "{pidfile}" ] && break; sleep 0.05; done; '
            f'cat "{pidfile}"'
        )
        # idempotent=False: a spawn that timed out ambiguously may still have
        # started its process — the resilient transport must never re-issue
        # it (a retry would double-spawn and orphan the first pidfile)
        result = self.transport.run(script, timeout=timeout, idempotent=False)
        if not result.ok or not result.stdout.strip():
            raise SpawnError(
                f"[{self.hostname}] spawn of task {task_id} failed: "
                f"{result.stderr.strip() or result.stdout.strip() or 'no pid produced'}"
            )
        try:
            pid = int(result.stdout.strip().splitlines()[-1])
        except ValueError:
            raise SpawnError(
                f"[{self.hostname}] could not parse spawned pid from "
                f"{result.stdout!r}"
            )
        log.info("[%s] spawned task %d as pid %d", self.hostname, task_id, pid)
        return pid

    def terminate(self, pid: int, mode: Termination = Termination.interrupt) -> bool:
        """Signal the whole process group; True if the signal was delivered."""
        mode = Termination(mode)
        result = self.transport.run(f"kill -{mode.value} -- -{int(pid)} 2>&1")
        return result.ok

    def running_tasks(self) -> Dict[int, int]:
        """Alive tasks on this host as ``{task_id: pid}``; prunes stale
        pidfiles. PID-reuse is guarded by requiring the task marker in the
        process's argv (reference matches screen session names instead,
        task_nursery.py:272-291)."""
        script = (
            f'cd "{self.run_dir}" 2>/dev/null || exit 0\n'
            "for f in task_*.pid; do\n"
            "  [ -e \"$f\" ] || continue\n"
            "  id=${f#task_}; id=${id%.pid}\n"
            "  pid=$(cat \"$f\" 2>/dev/null)\n"
            "  if [ -n \"$pid\" ] && kill -0 \"$pid\" 2>/dev/null && "
            f"grep -qa \"{TASK_MARKER_PREFIX}$id:\" \"/proc/$pid/cmdline\" 2>/dev/null; then\n"
            "    echo \"$id $pid\"\n"
            "  else\n"
            "    rm -f \"$f\"\n"
            "  fi\n"
            "done"
        )
        result = self.transport.run(script)
        tasks: Dict[int, int] = {}
        if result.ok:
            for line in result.stdout_lines():
                try:
                    task_id, pid = line.split()
                    tasks[int(task_id)] = int(pid)
                except ValueError:
                    continue
        return tasks

    def is_alive(self, task_id: int) -> bool:
        return task_id in self.running_tasks()

    def fetch_log(self, task_id: int, tail: Optional[int] = None) -> str:
        """Reference: task_nursery.fetch_log :294-315 (cat or tail the log)."""
        logfile = f"{self.log_dir}/task_{task_id}.log"
        cmd = f'tail -n {int(tail)} "{logfile}"' if tail else f'cat "{logfile}"'
        result = self.transport.run(cmd)
        if not result.ok:
            raise TransportError(
                f"[{self.hostname}] no log for task {task_id}: {result.stderr.strip()}"
            )
        return result.stdout

    def remove_log(self, task_id: int) -> None:
        self.transport.run(f'rm -f "{self.log_dir}/task_{task_id}.log"')

    # -- generic process ops (protection handlers) -------------------------
    def kill_pid(self, pid: int, sig: int = 9, sudo: bool = False) -> bool:
        """Reference: User/SudoProcessKillingBehaviour (kill / sudo kill)."""
        prefix = "sudo " if sudo else ""
        return self.transport.run(f"{prefix}kill -{int(sig)} {int(pid)} 2>&1").ok

    def process_owner(self, pid: int) -> Optional[str]:
        """Reference: GPUMonitor._get_process_owner via `ps` (:94-107)."""
        result = self.transport.run(f"ps --no-headers -o user -p {int(pid)}")
        owner = result.stdout.strip()
        return owner if result.ok and owner else None

    def process_owners(self, pids: List[int]) -> Dict[int, str]:
        """Batched owner lookup — ONE remote command for any number of PIDs
        (the reference issues one SSH round-trip per PID, flagged as the hot
        spot in SURVEY.md §3.2)."""
        if not pids:
            return {}
        pid_list = ",".join(str(int(p)) for p in pids)
        result = self.transport.run(f"ps --no-headers -o pid,user -p {pid_list}")
        owners: Dict[int, str] = {}
        for line in result.stdout_lines():
            try:
                pid_str, user = line.split()
                owners[int(pid_str)] = user
            except ValueError:
                continue
        return owners

    # -- PTY ops (MessageSendingBehaviour) ---------------------------------
    def pty_sessions(self) -> List[Tuple[str, str]]:
        """(user, tty) pairs of interactive sessions (reference:
        core/ssh.node_tty_sessions via `who`, ssh.py:148)."""
        result = self.transport.run("who -s")
        sessions: List[Tuple[str, str]] = []
        for line in result.stdout_lines():
            fields = line.split()
            if len(fields) >= 2:
                sessions.append((fields[0], fields[1]))
        return sessions

    def write_to_ptys(self, ttys: List[str], message: str) -> None:
        """One merged remote command for all target PTYs (reference merges
        per-tty `echo | tee /dev/tty` commands, MessageSendingBehaviour.py:51)."""
        if not ttys:
            return
        devices = " ".join(f"/dev/{tty}" for tty in ttys)
        self.transport.run(f"printf '%s\\n' {shlex.quote(message)} | tee {devices} > /dev/null")


class OpsFactory:
    """Builds HostOps per (host, user) — the seam services depend on.

    The default implementation wraps the TransportManager; tests install a
    fake that returns FakeHostOps bound to an in-memory cluster.
    """

    def __init__(self, transport_manager=None) -> None:
        self._manager = transport_manager

    @property
    def manager(self):
        if self._manager is None:
            from .transport.base import get_transport_manager

            self._manager = get_transport_manager()
        return self._manager

    def ops_for(self, hostname: str, user: Optional[str] = None) -> HostOps:
        return HostOps(self.manager.for_host(hostname, user=user))

    @property
    def hostnames(self) -> List[str]:
        return self.manager.hostnames


# ---------------------------------------------------------------------------
_factory: Optional[OpsFactory] = None


def get_ops_factory() -> OpsFactory:
    """Process-wide factory used by controllers/services; tests swap in a
    FakeOpsFactory via :func:`set_ops_factory`."""
    global _factory
    if _factory is None:
        _factory = OpsFactory()
    return _factory


def set_ops_factory(factory: Optional[OpsFactory]) -> None:
    global _factory
    _factory = factory
