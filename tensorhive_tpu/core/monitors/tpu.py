"""TPU chip monitor.

Reference: tensorhive/core/monitors/GPUMonitor.py:10-242 — three SSH layers
per tick (``--query-gpu`` CSV, per-UUID ``pmon`` scripts, one ``ps`` per
PID). The TPU rebuild collapses all of it into the single-round-trip probe
(see probe.py) and maps the results onto the exclusive-ownership model of
TPU chips: a chip's "processes" list is derived from which PIDs hold the
accelerator device node open — the libtpu lock analog of CUDA contexts
(SURVEY.md §7, BASELINE.json north_star "inspecting libtpu PIDs instead of
CUDA contexts").
"""
from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Dict, Optional

from ...config import Config, HostConfig, get_config
from ...observability import get_registry
from ..managers.infrastructure import LEASE_DEREGISTERED, chip_uid
from .base import Monitor
from .probe import ProbeSample, collect_probe_samples, probe_command

if TYPE_CHECKING:
    from ..managers.infrastructure import InfrastructureManager
    from ..transport.base import TransportManager

log = logging.getLogger(__name__)

# the probe monitor owns the per-host consecutive-failure streak: the raw
# signal behind the breaker/health state machines, exported so dashboards
# can see a host flapping BEFORE it trips anything
_CONSECUTIVE_FAILURES = get_registry().gauge(
    "tpuhive_probe_consecutive_failures",
    "Consecutive failed probe rounds per host (0 = healthy).",
    labels=("host",))


class TpuMonitor(Monitor):
    key = "TPU"

    def __init__(self, config: Optional[Config] = None) -> None:
        self.config = config or get_config()
        self._command = probe_command()
        #: latest parsed samples, shared with CpuMonitor to avoid a second
        #: round-trip (the probe already carries cpu/mem counters)
        self.last_samples: Dict[str, ProbeSample] = {}
        self._restricted_warned: set = set()

    def update(self, transports: "TransportManager", infra: "InfrastructureManager") -> None:
        # hybrid fan-out (docs/ROBUSTNESS.md "Host membership & leases"):
        # agent-enabled hosts push their telemetry through POST
        # /api/agent/report and carry a heartbeat lease — the SSH probe
        # must issue ZERO round-trips to them. Legacy hosts keep the pull
        # path unchanged.
        skip = self.agent_hosts(infra)
        targets = [h for h in transports.hostnames if h not in skip]
        samples = collect_probe_samples(transports, self._command,
                                        hostnames=targets)
        self.last_samples = {h: s for h, s in samples.items() if s is not None}
        for hostname, sample in samples.items():
            if sample is None:
                # one failed round = ONE health event (the old code dropped
                # both subtrees; now the last-known-good data is retained
                # and the host is marked degraded/unreachable instead)
                streak = infra.record_probe_failure(hostname)
                _CONSECUTIVE_FAILURES.labels(host=hostname).set(streak)
                continue
            _CONSECUTIVE_FAILURES.labels(host=hostname).set(0)
            if sample.restricted > 0 and hostname not in self._restricted_warned:
                self._restricted_warned.add(hostname)
                log.warning(
                    "probe on %s runs unprivileged: %d processes were not "
                    "inspectable — chip ownership may be incomplete; grant "
                    "passwordless sudo for the probe to fix this", hostname,
                    sample.restricted,
                )
            infra.update_subtree(hostname, self.key, self._chip_subtree(hostname, sample))
            infra.update_subtree(hostname, "WARNINGS",
                                 self._host_warnings(hostname, sample))

    # ------------------------------------------------------------------
    def agent_hosts(self, infra: "InfrastructureManager") -> set:
        """Hosts the SSH fan-out must skip: statically configured with
        ``agent = true`` OR dynamically joined through the report endpoint
        (lease source ``agent``, not deregistered)."""
        agents = {name for name, cfg in self.config.hosts.items()
                  if getattr(cfg, "agent", False)}
        for name, lease in infra.host_leases().items():
            if lease["source"] == "agent" and lease["state"] != LEASE_DEREGISTERED:
                agents.add(name)
        return agents

    # ------------------------------------------------------------------
    def _host_warnings(self, hostname: str, sample: ProbeSample) -> list:
        return host_warnings(hostname, sample)

    def _chip_subtree(self, hostname: str, sample: ProbeSample) -> Dict[str, Dict]:
        return chip_subtree(hostname, sample, self.config.hosts.get(hostname))


def host_warnings(hostname: str, sample: ProbeSample) -> list:
    """Per-host health warnings surfaced through /nodes and the
    dashboard. Blind telemetry must be visible: a TPU host whose sysfs
    counters are absent reports ANY-workload utilization as idle, which
    an operator cannot distinguish from a healthy quiet fleet unless
    it is said out loud (VERDICT r3 weak #7). Module-level because the
    agent-report path (controllers/agent.py) builds the same subtrees."""
    warnings = []
    if sample.chips and sample.sysfs_status != "ok":
        warnings.append({
            "key": "sysfs_absent",
            "message": (
                "no per-chip sysfs counters (/sys/class/accel): "
                "utilization of non-cooperating workloads is invisible "
                "on this host — check the TPU kernel driver"),
        })
    return warnings


def chip_subtree(hostname: str, sample: ProbeSample,
                 host_cfg: Optional[HostConfig] = None) -> Dict[str, Dict]:
    """Build the per-host TPU subtree from one parsed probe sample — shared
    between the SSH pull path (TpuMonitor) and the agent push path."""
    accel_type = host_cfg.accelerator_type if host_cfg else ""
    slice_name = host_cfg.slice_name if host_cfg else ""
    topology = (host_cfg.topology if host_cfg else "") or ""
    chips: Dict[str, Dict] = {}
    for chip in sample.chips:
        uid = chip_uid(hostname, chip.index)
        processes = []
        for pid in chip.pids:
            proc = sample.procs.get(pid, {})
            processes.append({
                "pid": pid,
                "user": proc.get("user", ""),
                "command": proc.get("cmd", ""),
            })
        hbm_used = chip.hbm_used_bytes
        hbm_total = chip.hbm_total_bytes
        chips[uid] = {
            "uid": uid,
            "index": chip.index,
            "hostname": hostname,
            "name": f"{accel_type or 'TPU'} chip {chip.index}",
            "accelerator_type": accel_type,
            "slice_name": slice_name,
            "topology": topology,
            "dev": chip.dev,
            "hbm_used_mib": _to_mib(hbm_used),
            "hbm_total_mib": _to_mib(hbm_total),
            "hbm_util_pct": _pct(hbm_used, hbm_total),
            "duty_cycle_pct": chip.duty_cycle_pct,
            "metrics_age_s": chip.metrics_age_s,
            "processes": processes,
        }
    return chips


def _to_mib(value_bytes: Optional[int]) -> Optional[int]:
    return None if value_bytes is None else int(value_bytes // 2**20)


def _pct(used: Optional[int], total: Optional[int]) -> Optional[float]:
    if used is None or not total:
        return None
    return round(100.0 * used / total, 1)
