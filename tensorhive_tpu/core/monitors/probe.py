"""Node telemetry probe: one remote round-trip per host per tick.

This replaces the reference's telemetry shell layer, which cost *many* SSH
round-trips per host per tick: one ``nvidia-smi --query-gpu`` fan-out, one
``nvidia-smi pmon`` script per host, then **one ``ps`` call per running
process** (flagged "hot spot" in SURVEY.md §3.2; GPUMonitor.py:77-107). Here
a single self-describing probe executes on the managed host and emits one
JSON line covering everything: accelerator devices, holder PIDs, process
owners/commands, CPU jiffies, and memory — so a monitoring tick is exactly
one command per host.

Two interchangeable probe implementations emit the same schema:

* ``tpuhive-probe`` — native C++ binary (native/probe.cpp), preferred; it is
  the TPU-native analog of the reference's nvidia-smi dependency (SURVEY.md
  §2: the telemetry reader is where the native-component requirement bites).
* an inline Python 3 script (below), used automatically when the binary is
  not installed on the host — TPU VMs always ship python3.

Probe JSON schema (version 1)::

    {"v": 1,
     "chips":   [{"index": 0, "dev": "/dev/accel0", "pids": [123, ...]}, ...],
     "procs":   {"123": {"user": "alice", "cmd": "python train.py"}, ...},
     "cpu":     {"total": <jiffies>, "idle": <jiffies>, "ncpu": 8},
     "mem":     {"total_kb": N, "avail_kb": N},
     "metrics": {"0": {"hbm_used_bytes": N, "hbm_total_bytes": N,
                       "duty_cycle_pct": F, "age_s": F}, ...},
     "sysfs_metrics": {"0": {"hbm_used_bytes": N, "hbm_total_bytes": N,
                             "duty_cycle_pct": F}, ...}}

``chips`` come from accelerator device nodes (``/dev/accel*`` on TPU VMs,
``/dev/vfio/N`` on older stacks); holder PIDs from a ``/proc/*/fd`` scan —
the TPU analog of ``nvidia-smi pmon`` given that a TPU chip is held by one
process via the libtpu lock (SURVEY.md §7 "process adoption" risk).

Utilization comes from two sources, strongest first:

* ``sysfs_metrics`` — per-accel kernel/runtime counters under
  ``/sys/class/accel/accel<N>/device/`` (tpu-info-style), read directly by
  the probe. These see ANY workload — including intruders and jobs that
  never import this framework — matching the reference's ability to read
  any process's utilization from the driver (GPUMonitor.py:20-48). Hosts
  whose platform does not export the counters simply omit the key.
* ``metrics`` — runtime counters (HBM occupancy, duty cycle) read from
  ``~/.tpuhive/metrics/*.json`` drop-files refreshed by the workload-side
  telemetry emitter (tensorhive_tpu/telemetry); the fallback when the OS
  exposes nothing. Stale files (>120 s) are marked via ``age_s`` and
  ignored by the monitor.
"""
from __future__ import annotations

import base64
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ...observability import get_registry, get_tracer
from ...utils.exceptions import TelemetryError

# probe-level accounting on top of the transport layer's per-command
# histograms: a "round" is one fan-out to every host + parse, the unit the
# monitoring tick actually waits on
_ROUND_SECONDS = get_registry().histogram(
    "tpuhive_probe_round_seconds",
    "One probe round: fan-out to all hosts plus parsing.")
_ROUNDS_TOTAL = get_registry().counter(
    "tpuhive_probe_rounds_total", "Probe rounds executed.")
_PROBE_FAILURES = get_registry().counter(
    "tpuhive_probe_failures_total",
    "Per-host probe failures by reason (unreachable, unparseable).",
    labels=("host", "reason"))
_PROBE_HOSTS_OK = get_registry().gauge(
    "tpuhive_probe_hosts_ok",
    "Hosts whose last probe round produced a valid sample.")
_PROBE_LAST_ROUND_TS = get_registry().gauge(
    "tpuhive_probe_last_round_timestamp_seconds",
    "Unix time the last probe round completed — readiness and the "
    "probe_round_stale alert rule compare it against 3x the monitoring "
    "interval.")

PROBE_VERSION = 1
#: stable marker present in every probe invocation (fake transports match it)
PROBE_MARKER = "tpuhive_probe"
#: where `ensure deployed` installs the native binary on managed hosts
PROBE_REMOTE_PATH = "$HOME/.tpuhive/bin/tpuhive-probe"
#: drop-file directory for runtime metrics emitted by workloads
METRICS_DIR = "$HOME/.tpuhive/metrics"
#: runtime metric drop-files older than this are reported but flagged stale
METRICS_MAX_AGE_S = 120.0


# The fallback probe. Kept dependency-free, Python 3.6+, single JSON line on
# stdout. Mirrors native/probe.cpp — change both together (schema v1).
PYTHON_PROBE_SOURCE = r"""
import glob, json, os, pwd, time
out = {"v": 1, "chips": [], "procs": {}, "cpu": {}, "mem": {}, "metrics": {},
       "sysfs_metrics": {}, "restricted": 0}
devs = sorted(glob.glob("/dev/accel[0-9]*")) + sorted(glob.glob("/dev/vfio/[0-9]*"))
dev_index = {os.path.realpath(d): i for i, d in enumerate(devs)}
holders = {}
for pid in filter(str.isdigit, os.listdir("/proc")):
    fd_dir = "/proc/%s/fd" % pid
    try:
        fds = os.listdir(fd_dir)
    except PermissionError:
        out["restricted"] += 1
        continue
    except OSError:
        continue
    for fd in fds:
        try:
            target = os.readlink(os.path.join(fd_dir, fd))
        except OSError:
            continue
        if target in dev_index:
            holders.setdefault(dev_index[target], set()).add(int(pid))
for i, dev in enumerate(devs):
    out["chips"].append({"index": i, "dev": dev, "pids": sorted(holders.get(i, ()))})
pids = set()
for chip in out["chips"]:
    pids.update(chip["pids"])
for pid in pids:
    try:
        with open("/proc/%d/cmdline" % pid, "rb") as fh:
            cmd = fh.read().replace(b"\0", b" ").decode(errors="replace").strip()
        uid = os.stat("/proc/%d" % pid).st_uid
        try:
            user = pwd.getpwuid(uid).pw_name
        except KeyError:
            user = str(uid)
        out["procs"][str(pid)] = {"user": user, "cmd": cmd}
    except OSError:
        continue
try:
    with open("/proc/stat") as fh:
        parts = fh.readline().split()[1:]
    vals = [int(x) for x in parts]
    out["cpu"] = {"total": sum(vals), "idle": vals[3] + (vals[4] if len(vals) > 4 else 0),
                  "ncpu": os.cpu_count() or 1}
except (OSError, IndexError, ValueError):
    pass
try:
    info = {}
    with open("/proc/meminfo") as fh:
        for line in fh:
            key, _, rest = line.partition(":")
            info[key] = int(rest.split()[0])
    out["mem"] = {"total_kb": info.get("MemTotal", 0),
                  "avail_kb": info.get("MemAvailable", info.get("MemFree", 0))}
except OSError:
    pass
mdir = os.environ.get("TPUHIVE_METRICS_DIR") or os.path.expanduser("~/.tpuhive/metrics")
now = time.time()
try:
    names = sorted(os.listdir(mdir))
except OSError:
    names = []
for name in names:
    if not name.endswith(".json"):
        continue
    path = os.path.join(mdir, name)
    try:
        age = now - os.stat(path).st_mtime
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        continue
    if not isinstance(data, dict):
        continue
    for chip_index, metrics in data.items():
        if isinstance(metrics, dict):
            merged = dict(metrics)
            merged["age_s"] = round(age, 1)
            out["metrics"][str(chip_index)] = merged
sysdir = os.environ.get("TPUHIVE_SYSFS_DIR") or "/sys/class/accel"
try:
    accels = sorted(os.listdir(sysdir))
except OSError:
    accels = []
for name in accels:
    if not (name.startswith("accel") and name[5:].isdigit()):
        continue
    counters = {}
    for field in ("duty_cycle_pct", "hbm_used_bytes", "hbm_total_bytes"):
        try:
            with open(os.path.join(sysdir, name, "device", field)) as fh:
                counters[field] = float(fh.read().split()[0])
        except (OSError, ValueError, IndexError):
            continue
    if counters:
        out["sysfs_metrics"][name[5:]] = counters
out["sysfs_status"] = "ok" if out["sysfs_metrics"] else "absent"
print(json.dumps(out, separators=(",", ":")))
""".strip()


def probe_command() -> str:
    """Shell command: run the native probe if installed — privileged via
    passwordless sudo when available, because /proc/<pid>/fd of *other
    users'* processes is unreadable without it and chip-ownership data is
    exactly what the protection service needs (the probe reports how many
    processes it could not inspect via ``restricted``). Falls back to the
    inline Python probe when the binary is absent; the base64 wrapper
    survives any quoting the transport applies."""
    encoded = base64.b64encode(PYTHON_PROBE_SOURCE.encode()).decode()
    fallback = (
        f'python3 -c "import base64 as b;exec(b.b64decode(\'{encoded}\'))"'
    )
    # The metrics dir travels as an argv flag, NOT an env assignment: with
    # default sudoers (no SETENV tag) `sudo -n VAR=... cmd` is rejected
    # wholesale, which would silently degrade to the unprivileged probe and
    # leave chip-ownership incomplete. A plain NOPASSWD rule suffices for
    # this form. $HOME expands in the invoking user's shell before sudo runs.
    sudo_flags = '--metrics-dir "$HOME/.tpuhive/metrics"'
    return (
        f"sudo -n {PROBE_REMOTE_PATH} {sudo_flags} 2>/dev/null "
        f"|| {PROBE_REMOTE_PATH} 2>/dev/null "
        f"|| {fallback}  # {PROBE_MARKER}"
    )


@dataclass
class ChipSample:
    index: int
    dev: str = ""
    pids: List[int] = field(default_factory=list)
    hbm_used_bytes: Optional[int] = None
    hbm_total_bytes: Optional[int] = None
    duty_cycle_pct: Optional[float] = None
    metrics_age_s: Optional[float] = None
    #: where the utilization numbers came from: "sysfs" (kernel/runtime
    #: counters — sees ANY workload, cooperating or not), "dropfile"
    #: (self-reported telemetry), or None (no utilization available)
    metrics_source: Optional[str] = None


@dataclass
class ProbeSample:
    """Parsed, validated probe output for one host."""

    chips: List[ChipSample] = field(default_factory=list)
    procs: Dict[int, Dict[str, str]] = field(default_factory=dict)
    cpu_total: Optional[int] = None
    cpu_idle: Optional[int] = None
    ncpu: int = 1
    mem_total_kb: int = 0
    mem_avail_kb: int = 0
    #: processes whose /proc/<pid>/fd was unreadable (probe unprivileged);
    #: >0 means chip-ownership data may be incomplete
    restricted: int = 0
    #: "ok" when the probe read per-chip kernel/runtime counters, "absent"
    #: when the sysfs tree yielded nothing — absent means utilization for
    #: non-cooperating workloads is BLIND on this host, which the monitor
    #: surfaces as a warning instead of letting it look like idle chips
    sysfs_status: str = "absent"


def parse_probe_output(text: str) -> ProbeSample:
    """Parse one probe JSON line (analog of NvidiaSmiParser.parse_query_gpu_
    stdout + parse_pmon_stdout, tensorhive/core/utils/NvidiaSmiParser.py:101,
    :151 — both merged into one document here)."""
    line = _last_json_line(text)
    try:
        doc = json.loads(line)
    except json.JSONDecodeError as exc:
        raise TelemetryError(f"probe output is not valid JSON: {exc}: {line[:200]!r}")
    if not isinstance(doc, dict) or doc.get("v") != PROBE_VERSION:
        raise TelemetryError(f"unsupported probe schema: {doc if isinstance(doc, dict) else type(doc)}")
    try:
        return _build_sample(doc)
    except (KeyError, ValueError, TypeError) as exc:
        # structurally malformed documents (version-skewed probe binary) must
        # surface as TelemetryError so per-host isolation in the monitors holds
        raise TelemetryError(f"malformed probe document: {exc!r}: {line[:200]!r}")


def _build_sample(doc: Dict[str, Any]) -> ProbeSample:
    sample = ProbeSample()
    metrics = doc.get("metrics") or {}
    sysfs = doc.get("sysfs_metrics") or {}
    for raw in doc.get("chips") or []:
        chip = ChipSample(index=int(raw["index"]), dev=str(raw.get("dev", "")),
                          pids=[int(p) for p in raw.get("pids", [])])
        # utilization merges per FIELD, sysfs over drop-files: kernel
        # counters cover workloads that never import the telemetry emitter
        # (intruders, external jobs — reference parity: GPUMonitor reads
        # ANY process via the driver), but a platform exporting only
        # duty_cycle must not null out HBM occupancy a fresh drop-file
        # still carries.
        chip_metrics = metrics.get(str(chip.index))
        if isinstance(chip_metrics, dict):
            age = chip_metrics.get("age_s")
            chip.metrics_age_s = float(age) if age is not None else None
            if chip.metrics_age_s is None or chip.metrics_age_s <= METRICS_MAX_AGE_S:
                chip.hbm_used_bytes = _opt_int(chip_metrics.get("hbm_used_bytes"))
                chip.hbm_total_bytes = _opt_int(chip_metrics.get("hbm_total_bytes"))
                chip.duty_cycle_pct = _opt_float(chip_metrics.get("duty_cycle_pct"))
                if chip_metrics:
                    chip.metrics_source = "dropfile"
        chip_sysfs = sysfs.get(str(chip.index))
        if isinstance(chip_sysfs, dict) and chip_sysfs:
            for field, convert in (("hbm_used_bytes", _opt_int),
                                   ("hbm_total_bytes", _opt_int),
                                   ("duty_cycle_pct", _opt_float)):
                value = convert(chip_sysfs.get(field))
                if value is not None:
                    setattr(chip, field, value)
            chip.metrics_source = "sysfs"
        sample.chips.append(chip)

    for pid, info in (doc.get("procs") or {}).items():
        if isinstance(info, dict):
            sample.procs[int(pid)] = {
                "user": str(info.get("user", "")),
                "cmd": str(info.get("cmd", "")),
            }

    cpu = doc.get("cpu") or {}
    if "total" in cpu and "idle" in cpu:
        sample.cpu_total = int(cpu["total"])
        sample.cpu_idle = int(cpu["idle"])
        sample.ncpu = int(cpu.get("ncpu", 1) or 1)
    mem = doc.get("mem") or {}
    sample.mem_total_kb = int(mem.get("total_kb", 0) or 0)
    sample.mem_avail_kb = int(mem.get("avail_kb", 0) or 0)
    sample.restricted = int(doc.get("restricted", 0) or 0)
    # docs from older probe binaries lack the key: derive it from whether
    # any counters arrived, so absence stays loud across version skew
    sample.sysfs_status = str(
        doc.get("sysfs_status") or ("ok" if sysfs else "absent"))
    return sample


def collect_probe_samples(
    transports: Any, command: Optional[str] = None,
    hostnames: Optional[List[str]] = None,
) -> Dict[str, Optional[ProbeSample]]:
    """Fan the probe out to every managed host and parse replies; hosts that
    fail (unreachable or malformed output) map to None — the shared
    per-host-isolation path of both TpuMonitor and CpuMonitor.

    ``hostnames`` restricts the fan-out (hybrid monitoring: agent-enabled
    hosts push their own telemetry and must cost ZERO SSH round-trips here,
    docs/ROBUSTNESS.md "Host membership & leases"); None = every managed
    host."""
    import logging

    log = logging.getLogger(__name__)
    samples: Dict[str, Optional[ProbeSample]] = {}
    started = time.perf_counter()
    with get_tracer().span("probe.collect", kind="probe") as span:
        for hostname, result in transports.run_on_all(
                command or probe_command(), hostnames=hostnames).items():
            if not result.ok:
                log.warning("probe failed on %s: %s", hostname,
                            result.stderr.strip() or f"exit {result.exit_code}")
                _PROBE_FAILURES.labels(host=hostname, reason="unreachable").inc()
                samples[hostname] = None
                continue
            try:
                samples[hostname] = parse_probe_output(result.stdout)
            except TelemetryError as exc:
                log.warning("unparseable probe output from %s: %s", hostname, exc)
                _PROBE_FAILURES.labels(host=hostname, reason="unparseable").inc()
                samples[hostname] = None
        healthy = sum(1 for sample in samples.values() if sample is not None)
        span.attrs["hosts"] = str(len(samples))
        span.attrs["ok"] = str(healthy)
        if healthy < len(samples):
            span.status = "error"
    _ROUND_SECONDS.observe(time.perf_counter() - started)
    _ROUNDS_TOTAL.inc()
    _PROBE_HOSTS_OK.set(healthy)
    _PROBE_LAST_ROUND_TS.set(time.time())
    return samples


def render_probe_json(
    chips: List[Dict[str, Any]],
    procs: Dict[int, Dict[str, str]],
    cpu: Optional[Dict[str, int]] = None,
    mem: Optional[Dict[str, int]] = None,
    metrics: Optional[Dict[str, Dict[str, Any]]] = None,
    sysfs_status: str = "ok",
) -> str:
    """Serialize a schema-v1 probe document (used by the fake cluster so
    tests exercise the real parser path)."""
    return json.dumps(
        {"v": PROBE_VERSION, "chips": chips, "procs": {str(k): v for k, v in procs.items()},
         "cpu": cpu or {}, "mem": mem or {}, "metrics": metrics or {},
         "sysfs_status": sysfs_status},
        separators=(",", ":"),
    )


def _last_json_line(text: str) -> str:
    """The probe prints exactly one line, but login shells may prepend noise
    (motd on forced-command setups); take the last line that looks like JSON."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return line
    raise TelemetryError(f"no JSON object in probe output: {text[:200]!r}")


def _opt_int(value: Any) -> Optional[int]:
    return None if value is None else int(value)


def _opt_float(value: Any) -> Optional[float]:
    return None if value is None else float(value)
