"""CPU/RAM monitor.

Reference: tensorhive/core/monitors/CPUMonitor.py:7-37 — an ``awk`` over
``/proc/stat`` plus ``free -m`` per host per tick, stored under a
``CPU_{host}`` pseudo-UUID. Here the counters arrive for free inside the TPU
probe's single round-trip (probe.py), so this monitor consumes the
:class:`TpuMonitor`'s last samples instead of issuing its own commands; when
running standalone (TPU monitoring disabled) it falls back to fanning the
probe out itself.

CPU utilization derives from jiffy deltas between consecutive ticks — the
reference instead burned a 1-second remote ``sleep`` inside awk on every
tick to sample twice (CPUMonitor.py:10-14); diffing across ticks costs
nothing and is exact over the tick interval.
"""
from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Dict, Optional, Tuple

from .base import Monitor
from .probe import ProbeSample, collect_probe_samples, probe_command
from .tpu import TpuMonitor

if TYPE_CHECKING:
    from ..managers.infrastructure import InfrastructureManager
    from ..transport.base import TransportManager

log = logging.getLogger(__name__)


class CpuMonitor(Monitor):
    key = "CPU"

    def __init__(self, tpu_monitor: Optional[TpuMonitor] = None) -> None:
        self._tpu_monitor = tpu_monitor
        self._command = probe_command()
        # hostname -> (total_jiffies, idle_jiffies) from the previous tick
        self._prev: Dict[str, Tuple[int, int]] = {}

    def update(self, transports: "TransportManager", infra: "InfrastructureManager") -> None:
        samples = self._collect_samples(transports)
        for hostname, sample in samples.items():
            if sample is None:
                # record the health event only when this monitor ran the
                # probe round itself; chained behind TpuMonitor, that
                # monitor already counted this host's failure — a second
                # count here would double every streak
                if self._tpu_monitor is None:
                    infra.record_probe_failure(hostname)
                continue
            infra.update_subtree(hostname, self.key, self._cpu_subtree(hostname, sample))

    # ------------------------------------------------------------------
    def _collect_samples(self, transports: "TransportManager") -> Dict[str, Optional[ProbeSample]]:
        if self._tpu_monitor is not None:
            samples: Dict[str, Optional[ProbeSample]] = dict(self._tpu_monitor.last_samples)
            for hostname in transports.hostnames:
                samples.setdefault(hostname, None)
            return samples
        return collect_probe_samples(transports, self._command)

    def _cpu_subtree(self, hostname: str, sample: ProbeSample) -> Dict[str, Dict]:
        prev = self._prev.get(hostname)
        if sample.cpu_total is not None and sample.cpu_idle is not None:
            self._prev[hostname] = (sample.cpu_total, sample.cpu_idle)
        return cpu_subtree(hostname, sample, prev)


def cpu_subtree(hostname: str, sample: ProbeSample,
                prev: Optional[Tuple[int, int]] = None) -> Dict[str, Dict]:
    """Build the per-host CPU subtree from one parsed probe sample; the
    caller supplies the previous tick's ``(total, idle)`` jiffies (util is a
    delta). Module-level because the agent push path (controllers/agent.py)
    builds the same subtree from reported probe documents."""
    util_pct = None
    if sample.cpu_total is not None and sample.cpu_idle is not None and prev is not None:
        d_total = sample.cpu_total - prev[0]
        d_idle = sample.cpu_idle - prev[1]
        if d_total > 0:
            util_pct = round(100.0 * (d_total - d_idle) / d_total, 1)
    mem_total_mib = sample.mem_total_kb // 1024
    mem_used_mib = max(0, (sample.mem_total_kb - sample.mem_avail_kb) // 1024)
    return {
        f"CPU_{hostname}": {
            "name": f"CPU {hostname}",
            "ncpu": sample.ncpu,
            "util_pct": util_pct,
            "mem_total_mib": mem_total_mib,
            "mem_used_mib": mem_used_mib,
            "mem_util_pct": round(100.0 * mem_used_mib / mem_total_mib, 1)
            if mem_total_mib else None,
        }
    }
