"""TpuHiveManager: the composition root.

Reference: tensorhive/core/managers/TensorHiveManager.py:33-125 — a Singleton
that builds the infrastructure + SSH managers, instantiates enabled services
from config, and starts/stops them (wired from cli.py:111-148). Here the
singleton is an explicit module-level accessor (set in one place at boot,
resettable in tests) rather than a metaclass, and service construction is a
plain factory function so tests can compose managers with fakes directly.
"""
from __future__ import annotations

import logging
import threading
from typing import List, Optional

from ...config import Config, get_config
from ..services.base import Service
from ..services.monitoring import MonitoringService
from ..transport.base import TransportManager
from .infrastructure import InfrastructureManager
from .service_manager import ServiceManager

log = logging.getLogger(__name__)


class TpuHiveManager:
    def __init__(
        self,
        config: Optional[Config] = None,
        transport_manager: Optional[TransportManager] = None,
        services: Optional[List[Service]] = None,
    ) -> None:
        self.config = config or get_config()
        self.infrastructure_manager = InfrastructureManager(list(self.config.hosts))
        self.transport_manager = transport_manager or TransportManager(self.config)
        self.service_manager: Optional[ServiceManager] = None
        self._services_override = services
        self._started = False

    # -- boot sequence (reference TensorHiveManager.__init__ + cli.main) ----
    def test_connectivity(self) -> dict:
        """Probe every managed host (reference test_ssh, :47-69)."""
        return self.transport_manager.test_all_connections()

    def configure_services_from_config(self) -> None:
        services = (
            self._services_override
            if self._services_override is not None
            else instantiate_services_from_config(self.config)
        )
        self.service_manager = ServiceManager(
            services, self.infrastructure_manager, self.transport_manager
        )
        self.service_manager.configure_all_services()

    def init(self) -> None:
        if self.service_manager is None:
            self.configure_services_from_config()
        assert self.service_manager is not None
        if self.config.monitoring.deploy_native_probe and self.config.hosts:
            from ..monitors.deploy import deploy_probe

            statuses = deploy_probe(self.transport_manager)
            deployed = sum(statuses.values())
            log.info("native probe deployed to %d/%d hosts", deployed, len(statuses))
        self.service_manager.start_all_services()
        self._started = True

    def shutdown(self) -> None:
        if self.service_manager is not None and self._started:
            self.service_manager.shutdown_all_services()
        self.transport_manager.close()
        self._started = False


def instantiate_services_from_config(config: Config) -> List[Service]:
    """Build enabled services (reference
    TensorHiveManager.instantiate_services_from_config:71-110). Imports are
    local so optional subsystems don't pay import costs when disabled."""
    services: List[Service] = []
    if config.monitoring.enabled:
        services.append(MonitoringService(config=config))
    if config.protection.enabled:
        from ..services.protection import ProtectionService

        services.append(ProtectionService(config=config))
    if config.usage_logging.enabled:
        from ..services.usage_logging import UsageLoggingService

        services.append(UsageLoggingService(config=config))
    if config.job_scheduling.enabled:
        from ..services.job_scheduling import JobSchedulingService

        services.append(JobSchedulingService(config=config))
    if config.generation.enabled:
        from ..services.generation import GenerationService

        services.append(GenerationService(config=config))
    if config.history.enabled:
        from ..services.history import HistoryService

        services.append(HistoryService(config=config))
    if config.alerting.enabled:
        # alerting starts LAST (start order == list order): its service_down
        # rule has for_s=0, so every other daemon must be alive before the
        # first evaluation tick or boot fires a false critical
        from ..services.alerting import AlertingService

        services.append(AlertingService(config=config))
    return services



# ---------------------------------------------------------------------------
_instance: Optional[TpuHiveManager] = None
_instance_lock = threading.Lock()


def get_manager() -> TpuHiveManager:
    """Process-wide manager (reference Singleton metaclass,
    core/utils/Singleton.py:4-11); built lazily, replaceable in tests."""
    global _instance
    with _instance_lock:
        if _instance is None:
            _instance = TpuHiveManager()
        return _instance


def set_manager(manager: Optional[TpuHiveManager]) -> None:
    global _instance
    with _instance_lock:
        _instance = manager
