"""In-memory latest-telemetry store shared across services and the API.

Reference: tensorhive/core/managers/InfrastructureManager.py:8-78 — a plain
dict ``{host: {'GPU': {uuid: {...}}, 'CPU': {...}}}`` written by the monitor
thread and read by the API/protection/scheduler threads *without locks*,
relying on ``deepcopy`` on the read path (controllers/nodes.py:15). SURVEY.md
§7 flags that implicit contract as a thing to re-implement deliberately: here
every access goes through an RW lock and readers get deep copies, so torn
reads are impossible by construction rather than by CPython luck.

Node shape (TPU-flavored)::

    {host: {"TPU": {chip_uid: {"uid", "index", "hostname",
                               "accelerator_type", "hbm_used_mib",
                               "hbm_total_mib", "hbm_util_pct",
                               "duty_cycle_pct", "processes": [
                                   {"pid", "user", "command"}]}},
            "CPU": {f"CPU_{host}": {"util_pct", "mem_total_mib",
                                     "mem_used_mib", "mem_util_pct"}}}}

Chip UIDs are ``{hostname}:tpu:{index}`` — globally unique and stable across
reboots, playing the role the 40-char GPU UUID plays in the reference
(models/Reservation.py:54 asserts on it; here Resource rows store this uid).
"""
from __future__ import annotations

import copy
import time
from typing import Dict, List, Optional

from ...utils.threading import RWLock

#: host health states, surfaced in every snapshot under the ``HEALTH`` key:
#: ``unknown`` (never successfully probed), ``ok`` (fresh telemetry),
#: ``degraded`` (1..unreachable_after-1 consecutive probe failures — the
#: last-known-good subtrees are RETAINED with a staleness age instead of
#: being dropped), ``unreachable`` (>= unreachable_after consecutive
#: failures). The reference left stale values in place indefinitely with no
#: marker; round 1 of this rebuild dropped them, which threw away the
#: last-known-good picture operators need to debug a dead node. This keeps
#: both truths: the data AND how stale it is.
HOST_UNKNOWN, HOST_OK, HOST_DEGRADED, HOST_UNREACHABLE = (
    "unknown", "ok", "degraded", "unreachable")

#: executable basenames never treated as foreign/intruding (reference
#: InfrastructureManager.ignored_processes: Xorg and friends; the TPU
#: equivalents are the platform daemons that idle-hold devices). Matching is
#: on the exact basename of argv[0] — substring matching over the command
#: line would let any user process exempt itself from intruder detection by
#: putting an ignored name in its arguments.
DEFAULT_IGNORED_PROCESSES = (
    "tpu-runtime",
    "tpuhive-probe",
)


def chip_uid(hostname: str, index: int) -> str:
    return f"{hostname}:tpu:{index}"


class InfrastructureManager:
    """Thread-safe latest-metrics store; monitors replace whole per-host
    subtrees, readers receive snapshots."""

    #: consecutive probe failures after which ``degraded`` becomes
    #: ``unreachable`` (aligned with the circuit-breaker default threshold)
    unreachable_after: int = 3

    def __init__(self, hostnames: Optional[List[str]] = None) -> None:
        self._lock = RWLock()
        self._infra: Dict[str, Dict] = {name: {} for name in (hostnames or [])}
        #: hostname -> {state, last_seen_ts, consecutive_failures, last_error}
        self._health: Dict[str, Dict] = {
            name: self._fresh_health() for name in (hostnames or [])}
        self.ignored_processes: List[str] = list(DEFAULT_IGNORED_PROCESSES)

    @staticmethod
    def _fresh_health() -> Dict:
        return {"state": HOST_UNKNOWN, "last_seen_ts": None,
                "consecutive_failures": 0, "last_error": ""}

    # -- write path (monitors) ---------------------------------------------
    def update_subtree(self, hostname: str, key: str, subtree: Dict) -> None:
        """Atomically replace one monitor's subtree for one host (reference
        monitors assign whole ``['GPU']`` dicts, GPUMonitor.py:92). A write
        is evidence of a successful probe: the host's health flips to ``ok``
        and its last-known-good stamp refreshes."""
        with self._lock.write():
            self._infra.setdefault(hostname, {})[key] = subtree
            health = self._health.setdefault(hostname, self._fresh_health())
            health.update(state=HOST_OK, last_seen_ts=time.time(),
                          consecutive_failures=0, last_error="")

    def record_probe_failure(self, hostname: str, error: str = "") -> int:
        """One failed probe round for ``hostname``: the consecutive-failure
        streak grows, state degrades (``degraded`` → ``unreachable`` at
        ``unreachable_after``), and the last-known-good subtrees stay in
        place with their staleness age. Returns the new streak."""
        with self._lock.write():
            health = self._health.setdefault(hostname, self._fresh_health())
            health["consecutive_failures"] += 1
            health["state"] = (
                HOST_UNREACHABLE
                if health["consecutive_failures"] >= self.unreachable_after
                else HOST_DEGRADED)
            health["last_error"] = error
            return health["consecutive_failures"]

    def record_probe_success(self, hostname: str) -> None:
        """Reset a host's streak without writing telemetry (monitors that
        write subtrees get this implicitly via :meth:`update_subtree`)."""
        with self._lock.write():
            health = self._health.setdefault(hostname, self._fresh_health())
            health.update(state=HOST_OK, last_seen_ts=time.time(),
                          consecutive_failures=0, last_error="")

    def mark_unreachable(self, hostname: str, key: str) -> None:
        """Compatibility shim for the old drop-the-subtree API: now records
        one probe failure and RETAINS the last-known-good data (``key`` is
        ignored — health is per host, not per subtree)."""
        self.record_probe_failure(hostname)

    # -- read path ----------------------------------------------------------
    def _health_view(self, hostname: str, now: Optional[float] = None) -> Dict:
        """Computed HEALTH entry for one host; caller holds the read lock."""
        health = self._health.get(hostname) or self._fresh_health()
        last_seen = health["last_seen_ts"]
        return {
            "state": health["state"],
            "last_seen_ts": last_seen,
            "staleness_s": (round((now or time.time()) - last_seen, 1)
                            if last_seen is not None else None),
            "consecutive_failures": health["consecutive_failures"],
            "last_error": health["last_error"],
        }

    def host_health(self, now: Optional[float] = None) -> Dict[str, Dict]:
        """{hostname: computed HEALTH entry} — staleness evaluated at
        ``now`` (injectable for deterministic tests)."""
        with self._lock.read():
            return {name: self._health_view(name, now) for name in self._infra}

    def host_state(self, hostname: str) -> str:
        with self._lock.read():
            health = self._health.get(hostname)
            return health["state"] if health else HOST_UNKNOWN

    @property
    def infrastructure(self) -> Dict[str, Dict]:
        """Deep-copied snapshot of everything, each node carrying a computed
        ``HEALTH`` entry (state + staleness of the last-known-good data)."""
        with self._lock.read():
            now = time.time()
            snapshot = copy.deepcopy(self._infra)
            for hostname, node in snapshot.items():
                node["HEALTH"] = self._health_view(hostname, now)
            return snapshot

    def node(self, hostname: str) -> Dict:
        with self._lock.read():
            node = copy.deepcopy(self._infra.get(hostname, {}))
            node["HEALTH"] = self._health_view(hostname)
            return node

    @property
    def hostnames(self) -> List[str]:
        with self._lock.read():
            return list(self._infra)

    # -- process queries (reference InfrastructureManager.py:34-78) ---------
    def node_tpu_processes(self, hostname: str) -> Dict[str, List[Dict]]:
        """``{chip_uid: [process, ...]}`` for one host, ignored processes
        filtered out (reference node_gpu_processes)."""
        with self._lock.read():
            chips = self._infra.get(hostname, {}).get("TPU", {})
            result: Dict[str, List[Dict]] = {}
            for uid, chip in chips.items():
                procs = [
                    copy.deepcopy(p)
                    for p in chip.get("processes", [])
                    if not self._ignored(p.get("command", ""))
                ]
                result[uid] = procs
            return result

    def all_nodes_with_tpu_processes(self) -> Dict[str, Dict[str, List[Dict]]]:
        """Reference InfrastructureManager.all_nodes_with_gpu_processes:63 —
        but only hosts with FRESH telemetry: now that last-known-good data is
        retained for degraded/unreachable hosts, the protection path must not
        act (kill, email) on a process list that may be minutes dead."""
        return {host: self.node_tpu_processes(host) for host in self.hostnames
                if self.host_state(host) not in (HOST_DEGRADED, HOST_UNREACHABLE)}

    def find_chip(self, uid: str) -> Optional[Dict]:
        """Locate a chip's metrics dict by uid across all hosts."""
        with self._lock.read():
            for node in self._infra.values():
                chip = node.get("TPU", {}).get(uid)
                if chip is not None:
                    return copy.deepcopy(chip)
        return None

    def find_chip_hostname(self, uid: str) -> Optional[str]:
        """Reference InfrastructureManager.get_gpu_uid inverse lookup."""
        with self._lock.read():
            for hostname, node in self._infra.items():
                if uid in node.get("TPU", {}):
                    return hostname
        return None

    def _ignored(self, command: str) -> bool:
        argv0 = command.split()[0] if command.split() else ""
        basename = argv0.rsplit("/", 1)[-1]
        return basename in self.ignored_processes
