"""In-memory latest-telemetry store shared across services and the API.

Reference: tensorhive/core/managers/InfrastructureManager.py:8-78 — a plain
dict ``{host: {'GPU': {uuid: {...}}, 'CPU': {...}}}`` written by the monitor
thread and read by the API/protection/scheduler threads *without locks*,
relying on ``deepcopy`` on the read path (controllers/nodes.py:15). SURVEY.md
§7 flags that implicit contract as a thing to re-implement deliberately: here
every access goes through an RW lock and readers get deep copies, so torn
reads are impossible by construction rather than by CPython luck.

Node shape (TPU-flavored)::

    {host: {"TPU": {chip_uid: {"uid", "index", "hostname",
                               "accelerator_type", "hbm_used_mib",
                               "hbm_total_mib", "hbm_util_pct",
                               "duty_cycle_pct", "processes": [
                                   {"pid", "user", "command"}]}},
            "CPU": {f"CPU_{host}": {"util_pct", "mem_total_mib",
                                     "mem_used_mib", "mem_util_pct"}}}}

Chip UIDs are ``{hostname}:tpu:{index}`` — globally unique and stable across
reboots, playing the role the 40-char GPU UUID plays in the reference
(models/Reservation.py:54 asserts on it; here Resource rows store this uid).
"""
from __future__ import annotations

import copy
import time
from typing import Dict, List, Optional

from ...observability import get_registry
from ...utils.threading import RWLock

#: host health states, surfaced in every snapshot under the ``HEALTH`` key:
#: ``unknown`` (never successfully probed), ``ok`` (fresh telemetry),
#: ``degraded`` (1..unreachable_after-1 consecutive probe failures — the
#: last-known-good subtrees are RETAINED with a staleness age instead of
#: being dropped), ``unreachable`` (>= unreachable_after consecutive
#: failures). The reference left stale values in place indefinitely with no
#: marker; round 1 of this rebuild dropped them, which threw away the
#: last-known-good picture operators need to debug a dead node. This keeps
#: both truths: the data AND how stale it is.
HOST_UNKNOWN, HOST_OK, HOST_DEGRADED, HOST_UNREACHABLE = (
    "unknown", "ok", "degraded", "unreachable")

#: membership lease states (docs/ROBUSTNESS.md "Host membership & leases").
#: Agent-enabled hosts push sequenced heartbeats; missed heartbeats walk the
#: lease ``live → suspect → unreachable → deregistered``. ``draining`` is an
#: admin-set overlay, not a lease state: a draining host keeps heartbeating
#: (stays ``live``) but takes no new work. Statically-configured hosts hold a
#: permanent ``live`` lease (their liveness is the PR 5 probe/health plane).
LEASE_LIVE, LEASE_SUSPECT, LEASE_UNREACHABLE, LEASE_DEREGISTERED = (
    "live", "suspect", "unreachable", "deregistered")
LEASE_DRAINING = "draining"  # effective-state label for the overlay

#: gauge encoding for ``tpuhive_host_lease_state{host}`` — draining (4) is
#: reported only while the underlying lease is live; a suspect/unreachable
#: draining host exports the more severe lease state
LEASE_STATE_VALUES = {
    LEASE_LIVE: 0, LEASE_SUSPECT: 1, LEASE_UNREACHABLE: 2,
    LEASE_DEREGISTERED: 3, LEASE_DRAINING: 4,
}

_LEASE_STATE = get_registry().gauge(
    "tpuhive_host_lease_state",
    "Membership lease state per host: 0=live 1=suspect 2=unreachable "
    "3=deregistered 4=draining (docs/ROBUSTNESS.md).",
    labels=("host",))

#: shared with controllers/agent.py, which stamps the ``bad_token`` outcome
#: before the report ever reaches the manager
AGENT_REPORTS = get_registry().counter(
    "tpuhive_agent_reports_total",
    "Agent membership reports by outcome "
    "(accepted/duplicate/out_of_order/bad_token).",
    labels=("host", "outcome"))

#: executable basenames never treated as foreign/intruding (reference
#: InfrastructureManager.ignored_processes: Xorg and friends; the TPU
#: equivalents are the platform daemons that idle-hold devices). Matching is
#: on the exact basename of argv[0] — substring matching over the command
#: line would let any user process exempt itself from intruder detection by
#: putting an ignored name in its arguments.
DEFAULT_IGNORED_PROCESSES = (
    "tpu-runtime",
    "tpuhive-probe",
)


def chip_uid(hostname: str, index: int) -> str:
    return f"{hostname}:tpu:{index}"


class InfrastructureManager:
    """Thread-safe latest-metrics store; monitors replace whole per-host
    subtrees, readers receive snapshots."""

    #: consecutive probe failures after which ``degraded`` becomes
    #: ``unreachable`` (aligned with the circuit-breaker default threshold)
    unreachable_after: int = 3

    def __init__(self, hostnames: Optional[List[str]] = None) -> None:
        self._lock = RWLock()
        self._infra: Dict[str, Dict] = {name: {} for name in (hostnames or [])}
        #: hostname -> {state, last_seen_ts, consecutive_failures, last_error}
        self._health: Dict[str, Dict] = {
            name: self._fresh_health() for name in (hostnames or [])}
        #: hostname -> membership lease record; static members hold a
        #: permanent live lease (never swept), agent members are swept by
        #: :meth:`sweep_leases`. Deregistered hosts keep a tombstone here
        #: (so replayed reports stay detectable) but vanish from ``_infra``.
        now = time.time()
        self._leases: Dict[str, Dict] = {
            name: self._fresh_lease("static", now) for name in (hostnames or [])}
        self.ignored_processes: List[str] = list(DEFAULT_IGNORED_PROCESSES)

    @staticmethod
    def _fresh_health() -> Dict:
        return {"state": HOST_UNKNOWN, "last_seen_ts": None,
                "consecutive_failures": 0, "last_error": ""}

    @staticmethod
    def _fresh_lease(source: str, now: float) -> Dict:
        return {"state": LEASE_LIVE, "draining": False, "source": source,
                "incarnation": "", "seq": -1, "last_report_ts": now,
                "registered_ts": now}

    # -- write path (monitors) ---------------------------------------------
    def update_subtree(self, hostname: str, key: str, subtree: Dict) -> None:
        """Atomically replace one monitor's subtree for one host (reference
        monitors assign whole ``['GPU']`` dicts, GPUMonitor.py:92). A write
        is evidence of a successful probe: the host's health flips to ``ok``
        and its last-known-good stamp refreshes."""
        with self._lock.write():
            self._infra.setdefault(hostname, {})[key] = subtree
            health = self._health.setdefault(hostname, self._fresh_health())
            health.update(state=HOST_OK, last_seen_ts=time.time(),
                          consecutive_failures=0, last_error="")

    def record_probe_failure(self, hostname: str, error: str = "") -> int:
        """One failed probe round for ``hostname``: the consecutive-failure
        streak grows, state degrades (``degraded`` → ``unreachable`` at
        ``unreachable_after``), and the last-known-good subtrees stay in
        place with their staleness age. Returns the new streak."""
        with self._lock.write():
            health = self._health.setdefault(hostname, self._fresh_health())
            health["consecutive_failures"] += 1
            health["state"] = (
                HOST_UNREACHABLE
                if health["consecutive_failures"] >= self.unreachable_after
                else HOST_DEGRADED)
            health["last_error"] = error
            return health["consecutive_failures"]

    def record_probe_success(self, hostname: str) -> None:
        """Reset a host's streak without writing telemetry (monitors that
        write subtrees get this implicitly via :meth:`update_subtree`)."""
        with self._lock.write():
            health = self._health.setdefault(hostname, self._fresh_health())
            health.update(state=HOST_OK, last_seen_ts=time.time(),
                          consecutive_failures=0, last_error="")

    def mark_unreachable(self, hostname: str, key: str) -> None:
        """Compatibility shim for the old drop-the-subtree API: now records
        one probe failure and RETAINS the last-known-good data (``key`` is
        ignored — health is per host, not per subtree)."""
        self.record_probe_failure(hostname)

    # -- membership lease plane (docs/ROBUSTNESS.md "Host membership &
    # leases") --------------------------------------------------------------
    def agent_report(self, hostname: str, incarnation: str, seq: int,
                     now: Optional[float] = None) -> str:
        """Apply one agent heartbeat; returns the outcome
        (``accepted``/``duplicate``/``out_of_order``).

        Idempotence contract: within one agent ``incarnation`` the sequence
        number is strictly monotonic — a repeat of the last seq is a
        ``duplicate`` (still counts as a heartbeat: at-least-once delivery
        must not kill a lease), anything older is ``out_of_order`` and
        changes nothing. A NEW incarnation resets the sequence space, so an
        agent restart or a re-join after deregistration starts clean with
        zero stale-sequence carryover. Acceptance is liveness evidence for
        the PR 5 health plane too (the SSH fan-out never probes this host)."""
        now = time.time() if now is None else now
        with self._lock.write():
            lease = self._leases.get(hostname)
            if lease is None or lease["source"] != "agent":
                draining = bool(lease and lease["draining"])
                lease = self._fresh_lease("agent", now)
                lease.update(draining=draining, incarnation=incarnation,
                             seq=seq, last_report_ts=now)
                self._leases[hostname] = lease
                outcome = "accepted"
            elif (lease["state"] == LEASE_DEREGISTERED
                  or incarnation != lease["incarnation"]):
                lease.update(incarnation=incarnation, seq=seq,
                             state=LEASE_LIVE, last_report_ts=now)
                outcome = "accepted"
            elif seq == lease["seq"]:
                lease["last_report_ts"] = now
                outcome = "duplicate"
            elif seq < lease["seq"]:
                outcome = "out_of_order"
            else:
                lease.update(seq=seq, state=LEASE_LIVE, last_report_ts=now)
                outcome = "accepted"
            if outcome == "accepted":
                self._infra.setdefault(hostname, {})
                health = self._health.setdefault(hostname, self._fresh_health())
                health.update(state=HOST_OK, last_seen_ts=now,
                              consecutive_failures=0, last_error="")
            self._export_lease_gauge(hostname, lease)
            AGENT_REPORTS.labels(host=hostname, outcome=outcome).inc()
            return outcome

    def sweep_leases(self, now: Optional[float] = None,
                     suspect_after_s: float = 4.0,
                     lease_ttl_s: float = 6.0,
                     deregister_after_s: float = 900.0) -> Dict[str, str]:
        """Walk every agent lease against ``now`` and apply transitions;
        returns ``{hostname: new_state}`` for hosts that changed. All ages
        are measured from the last accepted/duplicate report. Transitions
        mirror into the health plane so the existing protection/eligibility
        gates see them (suspect → degraded, expired → unreachable with the
        last-known-good snapshot retained); deregistration removes the host
        from snapshots entirely, leaving only the lease tombstone."""
        now = time.time() if now is None else now
        transitions: Dict[str, str] = {}
        with self._lock.write():
            for hostname, lease in list(self._leases.items()):
                if lease["source"] != "agent" or lease["state"] == LEASE_DEREGISTERED:
                    continue
                age = now - lease["last_report_ts"]
                if age >= deregister_after_s:
                    new_state = LEASE_DEREGISTERED
                elif age >= lease_ttl_s:
                    new_state = LEASE_UNREACHABLE
                elif age >= suspect_after_s:
                    new_state = LEASE_SUSPECT
                else:
                    new_state = LEASE_LIVE
                if new_state != lease["state"]:
                    lease["state"] = new_state
                    transitions[hostname] = new_state
                    if new_state == LEASE_DEREGISTERED:
                        self._infra.pop(hostname, None)
                        self._health.pop(hostname, None)
                    else:
                        health = self._health.setdefault(
                            hostname, self._fresh_health())
                        if new_state == LEASE_SUSPECT:
                            health["state"] = HOST_DEGRADED
                            health["last_error"] = (
                                f"heartbeat missed for {age:.1f}s")
                        elif new_state == LEASE_UNREACHABLE:
                            health["state"] = HOST_UNREACHABLE
                            health["last_error"] = (
                                f"lease expired ({age:.1f}s since last report)")
                        else:  # recovered without a report in between
                            health["state"] = HOST_OK
                self._export_lease_gauge(hostname, lease)
        return transitions

    def drain_host(self, hostname: str) -> Dict:
        """Admin drain: the host takes no new work (scheduler, protection and
        eligibility all honor it); running jobs are stopped gracefully by
        JobSchedulingService. Raises ``KeyError`` for unknown hosts."""
        return self._set_draining(hostname, True)

    def resume_host(self, hostname: str) -> Dict:
        return self._set_draining(hostname, False)

    def _set_draining(self, hostname: str, draining: bool) -> Dict:
        with self._lock.write():
            if hostname not in self._leases and hostname not in self._infra:
                raise KeyError(hostname)
            lease = self._leases.get(hostname)
            if lease is None:
                lease = self._fresh_lease("static", time.time())
                self._leases[hostname] = lease
            lease["draining"] = draining
            self._export_lease_gauge(hostname, lease)
            return self._lease_view(hostname)

    def host_draining(self, hostname: str) -> bool:
        with self._lock.read():
            lease = self._leases.get(hostname)
            return bool(lease and lease["draining"])

    def host_lease(self, hostname: str, now: Optional[float] = None) -> Dict:
        with self._lock.read():
            return self._lease_view(hostname, now)

    def host_leases(self, now: Optional[float] = None) -> Dict[str, Dict]:
        """{hostname: computed LEASE entry} over every known host, including
        deregistered tombstones (metrics/readyz stay honest about them)."""
        with self._lock.read():
            names = set(self._infra) | set(self._leases)
            return {name: self._lease_view(name, now) for name in sorted(names)}

    def _lease_view(self, hostname: str, now: Optional[float] = None) -> Dict:
        """Computed LEASE entry for one host; caller holds a lock."""
        lease = self._leases.get(hostname)
        if lease is None:
            return {"state": LEASE_LIVE, "effective": LEASE_LIVE,
                    "draining": False, "source": "static", "incarnation": "",
                    "seq": None, "last_report_ts": None, "age_s": None}
        effective = (LEASE_DRAINING
                     if lease["draining"] and lease["state"] == LEASE_LIVE
                     else lease["state"])
        age = None
        if lease["source"] == "agent":
            age = round((now or time.time()) - lease["last_report_ts"], 1)
        return {"state": lease["state"], "effective": effective,
                "draining": lease["draining"], "source": lease["source"],
                "incarnation": lease["incarnation"],
                "seq": lease["seq"] if lease["seq"] >= 0 else None,
                "last_report_ts": (lease["last_report_ts"]
                                   if lease["source"] == "agent" else None),
                "age_s": age}

    @staticmethod
    def _export_lease_gauge(hostname: str, lease: Dict) -> None:
        state = (LEASE_DRAINING
                 if lease["draining"] and lease["state"] == LEASE_LIVE
                 else lease["state"])
        _LEASE_STATE.labels(host=hostname).set(LEASE_STATE_VALUES[state])

    # -- read path ----------------------------------------------------------
    def _health_view(self, hostname: str, now: Optional[float] = None) -> Dict:
        """Computed HEALTH entry for one host; caller holds the read lock."""
        health = self._health.get(hostname) or self._fresh_health()
        last_seen = health["last_seen_ts"]
        return {
            "state": health["state"],
            "last_seen_ts": last_seen,
            "staleness_s": (round((now or time.time()) - last_seen, 1)
                            if last_seen is not None else None),
            "consecutive_failures": health["consecutive_failures"],
            "last_error": health["last_error"],
        }

    def host_health(self, now: Optional[float] = None) -> Dict[str, Dict]:
        """{hostname: computed HEALTH entry} — staleness evaluated at
        ``now`` (injectable for deterministic tests)."""
        with self._lock.read():
            return {name: self._health_view(name, now) for name in self._infra}

    def host_state(self, hostname: str) -> str:
        with self._lock.read():
            health = self._health.get(hostname)
            return health["state"] if health else HOST_UNKNOWN

    @property
    def infrastructure(self) -> Dict[str, Dict]:
        """Deep-copied snapshot of everything, each node carrying a computed
        ``HEALTH`` entry (state + staleness of the last-known-good data)."""
        with self._lock.read():
            now = time.time()
            snapshot = copy.deepcopy(self._infra)
            for hostname, node in snapshot.items():
                node["HEALTH"] = self._health_view(hostname, now)
                node["LEASE"] = self._lease_view(hostname, now)
            return snapshot

    def node(self, hostname: str) -> Dict:
        with self._lock.read():
            node = copy.deepcopy(self._infra.get(hostname, {}))
            node["HEALTH"] = self._health_view(hostname)
            node["LEASE"] = self._lease_view(hostname)
            return node

    @property
    def hostnames(self) -> List[str]:
        with self._lock.read():
            return list(self._infra)

    # -- process queries (reference InfrastructureManager.py:34-78) ---------
    def node_tpu_processes(self, hostname: str) -> Dict[str, List[Dict]]:
        """``{chip_uid: [process, ...]}`` for one host, ignored processes
        filtered out (reference node_gpu_processes)."""
        with self._lock.read():
            chips = self._infra.get(hostname, {}).get("TPU", {})
            result: Dict[str, List[Dict]] = {}
            for uid, chip in chips.items():
                procs = [
                    copy.deepcopy(p)
                    for p in chip.get("processes", [])
                    if not self._ignored(p.get("command", ""))
                ]
                result[uid] = procs
            return result

    def all_nodes_with_tpu_processes(self) -> Dict[str, Dict[str, List[Dict]]]:
        """Reference InfrastructureManager.all_nodes_with_gpu_processes:63 —
        but only hosts with FRESH telemetry: now that last-known-good data is
        retained for degraded/unreachable hosts, the protection path must not
        act (kill, email) on a process list that may be minutes dead.
        Draining hosts are excluded too: their jobs are being stopped
        gracefully by the scheduler, so protection actions would race the
        drain."""
        return {host: self.node_tpu_processes(host) for host in self.hostnames
                if self.host_state(host) not in (HOST_DEGRADED, HOST_UNREACHABLE)
                and not self.host_draining(host)}

    def find_chip(self, uid: str) -> Optional[Dict]:
        """Locate a chip's metrics dict by uid across all hosts."""
        with self._lock.read():
            for node in self._infra.values():
                chip = node.get("TPU", {}).get(uid)
                if chip is not None:
                    return copy.deepcopy(chip)
        return None

    def find_chip_hostname(self, uid: str) -> Optional[str]:
        """Reference InfrastructureManager.get_gpu_uid inverse lookup."""
        with self._lock.read():
            for hostname, node in self._infra.items():
                if uid in node.get("TPU", {}):
                    return hostname
        return None

    def _ignored(self, command: str) -> bool:
        argv0 = command.split()[0] if command.split() else ""
        basename = argv0.rsplit("/", 1)[-1]
        return basename in self.ignored_processes
