"""Queue-scheduling strategies.

Reference: tensorhive/core/scheduling.py:10-62 — ``Scheduler`` strategy
interface + ``GreedyScheduler``: take a queued job iff every chip its tasks
claim is free of upcoming reservations for at least
``schedule_queued_when_free_mins`` and not already taken by an earlier job
this round; skip a slot when the *owner's own* reservation is upcoming
(they'll use it themselves, GreedyScheduler.schedule_jobs:30-62).
"""
from __future__ import annotations

import logging
from datetime import timedelta
from typing import Callable, Dict, List, Optional, Set

from ..db.models.job import Job
from ..db.models.reservation import Reservation
from ..utils.timeutils import minutes_between, utcnow

log = logging.getLogger(__name__)

#: per-job eligible-host resolver: returns the set of hostnames the job's
#: owner may launch on, or None for "unrestricted" (reference
#: get_hosts_with_gpus_eligible_for_jobs, JobSchedulingService.py:174-195)
EligibleHostsFn = Callable[[Job], Optional[Set[str]]]


def expand_to_slice_uids(uids) -> Set[str]:
    """Expand claimed chip uids to their WHOLE slices.

    A TPU slice runs one SPMD program across all its chips (SURVEY.md §7
    "chip vs slice granularity": the reference matched single GPU UUIDs;
    a v5e-16 slice is 4 VMs × 4 chips acting as one device). A job that
    claims any chip of a named slice therefore contends with every
    reservation anywhere on that slice — scheduling it next to a foreign
    reservation on a sibling chip would wedge both workloads. Slice
    membership comes from the schema-v3 Resource columns; chips without a
    slice label behave exactly as before."""
    from ..db.models.resource import Resource

    expanded: Set[str] = set(uids)
    seen_slices: Set[str] = set()
    for uid in uids:
        row = Resource.get_by_uid(uid)
        if row is None or not row.slice_name or row.slice_name in seen_slices:
            continue
        seen_slices.add(row.slice_name)
        expanded.update(member.uid for member in
                        Resource.get_by_slice(row.slice_name))
    return expanded


class Scheduler:
    """Strategy: pick queued jobs to launch given per-chip free windows."""

    def schedule_jobs(
        self,
        queued_jobs: List[Job],
        required_free_minutes: float,
        at=None,
        eligible_hosts: Optional[EligibleHostsFn] = None,
    ) -> List[Job]:
        raise NotImplementedError


def _free_minutes_from_events(
    events: List[Reservation],
    horizon_mins: float,
    at,
    for_user_id: Optional[int] = None,
) -> float:
    """Free minutes until the first event in ``events`` not owned by
    ``for_user_id``, capped at ``horizon_mins``. ``events`` are this chip's
    non-cancelled reservations overlapping [at, at+horizon). An already-
    running foreign reservation (start <= at) yields 0."""
    foreign = [r for r in events if r.user_id != for_user_id]
    if not foreign:
        return horizon_mins
    free = min(minutes_between(at, r.start) for r in foreign)
    return min(horizon_mins, max(0.0, free))


def upcoming_events_by_chip(
    uids: Set[str],
    horizon_mins: float,
    at=None,
) -> Dict[str, List[Reservation]]:
    """ONE time-range query for every chip a scheduling round cares about
    (reference batches the same way: filter_by_uuids_and_time_range,
    JobSchedulingService.py:76-104). Round-2 issued two queries per chip per
    queued job per tick — O(jobs × chips) round-trips; this is O(1)."""
    at = at or utcnow()
    rows = Reservation.filter_by_uids_and_time_range(
        uids, start=at, end=at + timedelta(minutes=horizon_mins))
    by_chip: Dict[str, List[Reservation]] = {uid: [] for uid in uids}
    for row in rows:
        if not row.is_cancelled:
            by_chip[row.resource_id].append(row)
    return by_chip


def chip_free_minutes(
    uid: str,
    horizon_mins: float,
    at=None,
    for_user_id: Optional[int] = None,
) -> float:
    """Minutes until the chip's next active/non-cancelled reservation, capped
    at ``horizon_mins`` (reference check_current_gpu_slots,
    JobSchedulingService.py:76-104). A chip under a *current* reservation has
    0 free minutes. Reservations owned by ``for_user_id`` don't count —
    a user's queued job may run inside their own reserved window (reference
    GreedyScheduler treats the owner's own upcoming reservation as free,
    scheduling.py:48-56)."""
    at = at or utcnow()
    events = upcoming_events_by_chip({uid}, horizon_mins, at=at)[uid]
    return _free_minutes_from_events(events, horizon_mins, at, for_user_id)


class GreedyScheduler(Scheduler):
    """First-come-first-served over the queue in enqueue order.

    ``slice_exclusive`` (default): each job's chip claims are expanded to
    whole slices before the free-window check and before marking chips
    taken, so one scheduling round never lands two jobs — or a job and a
    foreign reservation — on the same slice."""

    HORIZON_MINS = 24 * 60.0

    def __init__(self, slice_exclusive: bool = True) -> None:
        self.slice_exclusive = slice_exclusive

    def _claimed(self, job: Job) -> Set[str]:
        uids = set(job.chip_uids)
        return expand_to_slice_uids(uids) if self.slice_exclusive else uids

    def schedule_jobs(
        self,
        queued_jobs: List[Job],
        required_free_minutes: float,
        at=None,
        eligible_hosts: Optional[EligibleHostsFn] = None,
    ) -> List[Job]:
        at = at or utcnow()
        taken: set = set()
        chosen: List[Job] = []
        claims = {job.id: self._claimed(job) for job in queued_jobs}
        all_uids = {uid for claim in claims.values() for uid in claim}
        # one reservation query for the whole round, however many jobs/chips
        events = upcoming_events_by_chip(all_uids, self.HORIZON_MINS, at=at) \
            if all_uids else {}
        for job in queued_jobs:
            if not self._hosts_eligible(job, eligible_hosts):
                continue
            uids = claims[job.id]
            if not uids:
                # no chip claims (CPU-only job): the host-eligibility gate
                # above is the whole check — reference launches chip-less
                # jobs only on eligible hosts too (JobSchedulingService.py
                # :174-195); without it a queued job on an unknown or
                # restricted host would bypass all gating
                chosen.append(job)
                continue
            ok = True
            for uid in uids:
                free = _free_minutes_from_events(
                    events[uid], self.HORIZON_MINS, at, job.user_id)
                if uid in taken or free < required_free_minutes:
                    ok = False
                    break
            if ok:
                taken.update(uids)
                chosen.append(job)
        return chosen

    @staticmethod
    def _hosts_eligible(job: Job, eligible_hosts: Optional[EligibleHostsFn]) -> bool:
        """Every task hostname must be eligible for the job's owner."""
        if eligible_hosts is None:
            return True
        hosts = eligible_hosts(job)
        if hosts is None:  # unrestricted user
            return True
        missing = {task.hostname for task in job.tasks} - hosts
        if missing:
            log.debug("job %d skipped: hosts %s not eligible", job.id, sorted(missing))
        return not missing
