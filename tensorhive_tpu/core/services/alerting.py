"""AlertingService: evaluate the alert rule engine each tick, fan out
transitions to notification sinks.

The reference had no alerting at all — its services died silently (SURVEY
§5). This daemon is deliberately thin: all rule/state logic lives in
tensorhive_tpu/observability/alerts.py (deterministically testable with a
fake clock), and subclassing :class:`Service` buys the tick histogram, the
overrun counter and the liveness stamps for free — so the alerting loop is
itself covered by the ``service_down`` rule and the readiness check like
any other daemon.

Sink fan-out happens here, outside the engine lock, with per-sink
isolation: one broken webhook must neither skip the log sink nor kill the
evaluating tick.
"""
from __future__ import annotations

import logging
from typing import List, Optional

from ...config import Config, get_config
from ...observability import get_registry
from ...observability.alerts import (
    AlertEngine,
    AlertSink,
    LogSink,
    WebhookSink,
    get_alert_engine,
)
from .base import Service

log = logging.getLogger(__name__)

_SINK_FAILURES = get_registry().counter(
    "tpuhive_alert_sink_failures_total",
    "Alert notifications a sink raised on (delivery is per-sink isolated).",
    labels=("sink",))


class AlertingService(Service):
    def __init__(self, config: Optional[Config] = None,
                 engine: Optional[AlertEngine] = None,
                 sinks: Optional[List[AlertSink]] = None) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.alerting.interval_s)
        self.engine = engine if engine is not None else get_alert_engine()
        self.sinks = sinks if sinks is not None else default_sinks(config)

    def do_run(self) -> None:
        for event in self.engine.evaluate():
            self.dispatch(event)

    def dispatch(self, event: dict) -> None:
        for sink in self.sinks:
            try:
                sink.notify(event)
            except Exception:
                log.exception("alert sink %s failed on %s -> %s",
                              sink.name, event.get("rule"), event.get("to"))
                _SINK_FAILURES.labels(sink=sink.name).inc()


def default_sinks(config: Config) -> List[AlertSink]:
    """Structured log sink always-on; webhook sink when configured."""
    sinks: List[AlertSink] = [LogSink()]
    if config.alerting.webhook_url:
        sinks.append(WebhookSink(
            config.alerting.webhook_url,
            timeout_s=config.alerting.webhook_timeout_s,
            retries=config.alerting.webhook_retries,
        ))
    return sinks
