"""UsageLoggingService: per-reservation utilization accounting.

Reference: tensorhive/core/services/UsageLoggingService.py:18-240 — during an
active reservation, append utilization samples to a per-reservation log under
the usage-log dir; when the reservation expires, average the samples into the
reservation row (``gpu_util_avg``/``mem_util_avg``) and apply the cleanup
action (1=remove, 2=hide via dot-prefix, 3=keep; ``LogFileCleanupAction``
:18). TPU metrics: duty-cycle (MXU activity) and HBM utilization.

Format divergence from the reference (which rewrites a whole JSON document
per sample): logs are **JSON-lines**, one sample object appended per tick —
O(1) I/O per sample instead of O(n) re-serialization (an 8-day reservation at
2 s cadence accumulates ~345k samples). ``KEEP``-mode files are renamed to
``<id>.done.jsonl`` after accounting so they are never re-processed.
"""
from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Dict, List, Optional

from ...config import Config, get_config
from ...db.models.reservation import Reservation
from ...observability import get_registry
from ...observability.accounting import get_tenant_meter
from ...utils.timeutils import isoformat, utcnow
from .base import Service

log = logging.getLogger(__name__)

_SAMPLES = get_registry().counter(
    "tpuhive_usage_samples_total",
    "Utilization samples appended to per-reservation usage logs.")
_ACCOUNTED = get_registry().counter(
    "tpuhive_usage_reservations_accounted_total",
    "Expired reservations whose usage averages were persisted.")

REMOVE, HIDE, KEEP = 1, 2, 3


class UsageLoggingService(Service):
    def __init__(self, config: Optional[Config] = None) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.usage_logging.interval_s)
        self.log_dir = Path(config.usage_log_dir)
        self.cleanup_action = config.usage_logging.log_cleanup_action

    def do_run(self) -> None:
        assert self.infrastructure_manager is not None, "service not injected"
        self.log_current_usage()
        self.handle_expired_logs()

    # -- sampling (reference log_current_usage :159) ------------------------
    def log_current_usage(self) -> None:
        active = Reservation.current_events()
        if not active:
            return
        self.log_dir.mkdir(parents=True, exist_ok=True)
        meter = get_tenant_meter()
        for reservation in active:
            chip = self.infrastructure_manager.find_chip(reservation.resource_id)
            if chip is None:
                continue
            duty = chip.get("duty_cycle_pct")
            sample = {
                "time": isoformat(utcnow()),
                "duty_cycle_pct": duty,
                "hbm_util_pct": chip.get("hbm_util_pct"),
            }
            self._append_sample(reservation.id, sample)
            if meter is not None:
                # reservation plane of the tenant attribution substrate
                # (docs/OBSERVABILITY.md "Tenant accounting"): one held
                # chip x the sampling cadence per tick, plus the
                # duty-cycle-weighted share actually exercised
                meter.charge_reservation(
                    self._owner_key(reservation),
                    chip_seconds=self.interval_s,
                    effective_chip_seconds=(
                        self.interval_s * duty / 100.0
                        if duty is not None else None))

    @staticmethod
    def _owner_key(reservation: Reservation) -> str:
        """Tenant key for a reservation: the owner's username (the same
        namespace serving's ``userKey`` lives in), ``user:<id>`` when the
        row outlived its user."""
        from ...db.models.user import User

        user = User.get_or_none(reservation.user_id)
        if user is not None and getattr(user, "username", None):
            return user.username
        return f"user:{reservation.user_id}"

    def _path(self, reservation_id: int) -> Path:
        return self.log_dir / f"{reservation_id}.jsonl"

    def _append_sample(self, reservation_id: int, sample: Dict) -> None:
        with open(self._path(reservation_id), "a") as fh:
            fh.write(json.dumps(sample) + "\n")
        _SAMPLES.inc()

    @staticmethod
    def _read_samples(path: Path) -> List[Dict]:
        samples: List[Dict] = []
        try:
            with open(path) as fh:
                for line in fh:
                    try:
                        samples.append(json.loads(line))
                    except ValueError:
                        continue  # torn write at crash time
        except OSError:
            pass
        return samples

    # -- expiry accounting (reference handle_expired_logs :196) -------------
    def handle_expired_logs(self) -> None:
        if not self.log_dir.is_dir():
            return
        now = utcnow()
        for path in sorted(self.log_dir.glob("[0-9]*.jsonl")):
            stem = path.name[:-len(".jsonl")]
            if not stem.isdigit():
                continue  # excludes <id>.done.jsonl markers
            reservation = Reservation.get_or_none(int(stem))
            if reservation is None:
                path.unlink(missing_ok=True)
                continue
            if reservation.end > now:
                continue  # still active
            self._persist_averages(reservation, self._read_samples(path))
            self._cleanup(path)
            _ACCOUNTED.inc()

    @staticmethod
    def _persist_averages(reservation: Reservation, samples: List[Dict]) -> None:
        def avg(key: str) -> Optional[float]:
            values = [s[key] for s in samples if s.get(key) is not None]
            return round(sum(values) / len(values), 1) if values else None

        reservation.duty_cycle_avg = avg("duty_cycle_pct")
        reservation.hbm_util_avg = avg("hbm_util_pct")
        reservation.save()
        log.info("reservation %d usage: duty=%s%% hbm=%s%%",
                 reservation.id, reservation.duty_cycle_avg, reservation.hbm_util_avg)

    def _cleanup(self, path: Path) -> None:
        if self.cleanup_action == REMOVE:
            path.unlink(missing_ok=True)
        elif self.cleanup_action == HIDE:
            path.rename(path.with_name("." + path.name))
        else:  # KEEP: retain content, mark accounted so it's never re-read
            path.rename(path.with_name(path.name[:-len(".jsonl")] + ".done.jsonl"))
