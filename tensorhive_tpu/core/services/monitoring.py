"""MonitoringService: poll all monitors against all hosts each tick.

Reference: tensorhive/core/services/MonitoringService.py:13-55 — every
``interval`` (2.0 s default, config.py:204) run each Monitor over the group
SSH connection and store results in InfrastructureManager, gevent-sleeping
the remainder. Identical responsibilities here; the transport fan-out is a
thread pool and monitors share the single-probe round-trip (monitors/probe.py).
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional

from ...config import Config, get_config
from ...observability import get_registry, get_tracer
from ..monitors.base import Monitor
from ..monitors.cpu import CpuMonitor
from ..monitors.tpu import TpuMonitor
from .base import Service

log = logging.getLogger(__name__)

_UPDATE_SECONDS = get_registry().histogram(
    "tpuhive_monitor_update_seconds",
    "Duration of one monitor.update() pass over all hosts.",
    labels=("monitor",))
_UPDATE_FAILURES = get_registry().counter(
    "tpuhive_monitor_update_failures_total",
    "Monitor passes that raised (per-monitor isolation kept the tick alive).",
    labels=("monitor",))


class MonitoringService(Service):
    def __init__(self, monitors: Optional[List[Monitor]] = None,
                 config: Optional[Config] = None) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.monitoring.interval_s)
        if monitors is None:
            monitors = default_monitors(config)
        self.monitors = monitors
        self._agent_config = config.agent

    def do_run(self) -> None:
        assert self.infrastructure_manager is not None, "service not injected"
        assert self.transport_manager is not None, "service not injected"
        self.sweep_leases()
        tracer = get_tracer()
        for monitor in self.monitors:
            monitor_name = type(monitor).__name__
            started = time.perf_counter()
            with tracer.span(f"monitor.{monitor_name}", kind="monitor") as span:
                try:
                    monitor.update(self.transport_manager,
                                   self.infrastructure_manager)
                except Exception:
                    # per-monitor isolation: CPU metrics survive a TPU-probe bug
                    log.exception("monitor %s failed", monitor_name)
                    _UPDATE_FAILURES.labels(monitor=monitor_name).inc()
                    span.status = "error"
            _UPDATE_SECONDS.labels(monitor=monitor_name).observe(
                time.perf_counter() - started)

    def sweep_leases(self, now: Optional[float] = None) -> None:
        """Advance the membership lease state machine one step
        (docs/ROBUSTNESS.md "Host membership & leases"); ``now`` is
        injectable so fake-clock tests can drive transitions
        deterministically. No-op while the agent plane is off (no token)."""
        agent = self._agent_config
        if not agent.enabled or not agent.token:
            return
        assert self.infrastructure_manager is not None
        transitions = self.infrastructure_manager.sweep_leases(
            now=now,
            suspect_after_s=agent.effective_suspect_after_s(),
            lease_ttl_s=agent.effective_lease_ttl_s(),
            deregister_after_s=agent.deregister_after_s)
        for hostname, state in transitions.items():
            log.warning("host %s membership lease -> %s", hostname, state)


def default_monitors(config: Config) -> List[Monitor]:
    """Monitor set per config flags (reference
    TensorHiveManager.instantiate_services_from_config enables GPU/CPU
    monitors independently)."""
    monitors: List[Monitor] = []
    tpu_monitor = None
    if config.monitoring.enable_tpu_monitor:
        tpu_monitor = TpuMonitor(config)
        monitors.append(tpu_monitor)
    if config.monitoring.enable_cpu_monitor:
        monitors.append(CpuMonitor(tpu_monitor=tpu_monitor))
    return monitors
