"""HistoryService: sample the metrics-history ring and refresh SLO gauges.

Deliberately thin, like AlertingService: all storage and arithmetic live in
tensorhive_tpu/observability/history.py and observability/slo.py
(deterministically testable with a fake clock); subclassing
:class:`Service` buys the tick histogram, the overrun counter and the
liveness stamps, so the sampler is itself covered by the ``service_down``
rule like any other daemon. SLO gauge refresh rides the same tick so the
``tpuhive_slo_*`` series stay current even when nothing scrapes
``/api/metrics`` (the scrape-time collector in slo.py covers the other
direction).
"""
from __future__ import annotations

import logging
import time
from typing import Optional

from ...config import Config, get_config
from ...observability.history import MetricsHistory, get_metrics_history
from .base import Service

log = logging.getLogger(__name__)


class HistoryService(Service):
    def __init__(self, config: Optional[Config] = None,
                 history: Optional[MetricsHistory] = None) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.history.sample_interval_s)
        self._history = history
        self._slo_enabled = config.slo.enabled

    def do_run(self) -> None:
        history = self._history if self._history is not None \
            else get_metrics_history()
        now = time.time()
        history.sample(now)
        if self._slo_enabled:
            from ...observability.slo import get_slo_engine

            get_slo_engine().evaluate(now)
