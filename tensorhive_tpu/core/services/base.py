"""Service base class.

Reference: tensorhive/core/services/Service.py (16 LoC) + StoppableThread —
a thread with an abstract ``inject`` hook through which ServiceManager pushes
the shared managers (ServiceManager.py:configure_all_services). Here the
injection is explicit and typed, and every service gets uniform tick timing:
the reference hand-rolled per-loop perf_counter bookkeeping in each service
(MonitoringService.py:38-54, ProtectionService.py:81) — that bookkeeping was
the *only* profiling the reference has (SURVEY.md §5 Tracing). It is kept,
centralized, and now feeds the shared metrics registry
(tensorhive_tpu/observability): tick durations land in a
``tpuhive_service_tick_seconds`` histogram, overruns in a counter, and each
tick records a span, so ``/api/metrics`` and ``/api/admin/traces`` expose
what used to die in debug logs.
"""
from __future__ import annotations

import logging
import time
from typing import TYPE_CHECKING, Dict, Optional

from ...observability import Histogram, get_registry, get_tracer
from ...utils.threading import StoppableThread

if TYPE_CHECKING:
    from ..managers.infrastructure import InfrastructureManager
    from ..transport.base import TransportManager

log = logging.getLogger(__name__)

# registered once at import; every Service instance feeds the child for its
# own service label, so all daemons share one exposition surface
_TICK_SECONDS = get_registry().histogram(
    "tpuhive_service_tick_seconds",
    "Duration of one service do_run() tick.", labels=("service",))
_TICKS_TOTAL = get_registry().counter(
    "tpuhive_service_ticks_total",
    "Completed service ticks (including failed ones).", labels=("service",))
_TICK_FAILURES = get_registry().counter(
    "tpuhive_service_tick_failures_total",
    "Service ticks that raised an exception.", labels=("service",))
_TICK_OVERRUNS = get_registry().counter(
    "tpuhive_service_tick_overruns_total",
    "Ticks that ran longer than the service interval (interval starvation).",
    labels=("service",))


class Service(StoppableThread):
    """Periodic daemon thread: ``do_run()`` every ``interval_s`` seconds.

    Subclasses implement :meth:`do_run`; the run loop measures each tick,
    records it into the metrics registry + span tracer, and sleeps out the
    interval remainder (interruptible by shutdown).
    """

    def __init__(self, interval_s: float, name: Optional[str] = None) -> None:
        super().__init__(name=name or type(self).__name__)
        self.interval_s = interval_s
        self.infrastructure_manager: Optional["InfrastructureManager"] = None
        self.transport_manager: Optional["TransportManager"] = None
        #: per-INSTANCE latency histogram backing the p50/p95/max
        #: introspection — private so a fresh service never reports another
        #: instance's history (the registry child is shared per label)
        self._tick_hist = Histogram()
        self.ticks_completed = 0
        self.tick_overruns = 0
        self._overrun_warned = False
        #: wall-clock stamps backing the readiness staleness check
        #: (observability/health.py): a service is "fresh" when its last
        #: completed tick — or, before the first completes, its run-loop
        #: entry — is within 3x the interval
        self.last_tick_ts: Optional[float] = None
        self.run_started_ts: Optional[float] = None

    def inject(self, infrastructure_manager: "InfrastructureManager",
               transport_manager: "TransportManager") -> None:
        """Receive shared managers (reference Service.inject)."""
        self.infrastructure_manager = infrastructure_manager
        self.transport_manager = transport_manager

    # -- loop ---------------------------------------------------------------
    def run(self) -> None:
        tracer = get_tracer()
        self.run_started_ts = time.time()
        while not self.stopped:
            started = time.perf_counter()
            span = tracer.start_span(f"tick.{self.name}", kind="tick",
                                     service=self.name)
            status = "ok"
            try:
                self.do_run()
            except Exception:
                # a crashing tick must not kill the daemon thread (the
                # reference would die silently here — its threads have no
                # guard and a monitor exception stops all monitoring)
                log.exception("%s tick failed", self.name)
                _TICK_FAILURES.labels(service=self.name).inc()
                status = "error"
            elapsed = time.perf_counter() - started
            tracer.end_span(span, status=status)
            self.record_tick(elapsed)
            remaining = self.interval_s - elapsed
            if remaining > 0:
                self.wait(remaining)
            else:
                self.record_overrun(elapsed)

    def record_tick(self, elapsed_s: float) -> None:
        """Account one tick (separate from run() so tests and manual tick
        drivers hit the identical bookkeeping path)."""
        self._tick_hist.observe(elapsed_s)
        _TICK_SECONDS.labels(service=self.name).observe(elapsed_s)
        _TICKS_TOTAL.labels(service=self.name).inc()
        self.ticks_completed += 1
        self.last_tick_ts = time.time()

    def record_overrun(self, elapsed_s: float) -> None:
        """A tick exceeded the interval: silent starvation of the poll
        cadence. Counted always; the FIRST overrun per service is a
        log.warning (the reference only ever debug-logged these, so a
        misconfigured interval was invisible in production logs)."""
        self.tick_overruns += 1
        _TICK_OVERRUNS.labels(service=self.name).inc()
        if not self._overrun_warned:
            self._overrun_warned = True
            log.warning(
                "%s tick overran its interval: %.3fs > %.3fs — the service "
                "is running back-to-back; further overruns log at debug",
                self.name, elapsed_s, self.interval_s)
        else:
            log.debug("%s tick overran interval: %.3fs > %.3fs",
                      self.name, elapsed_s, self.interval_s)

    def do_run(self) -> None:
        raise NotImplementedError

    # -- introspection ------------------------------------------------------
    def tick_latency_p50(self) -> Optional[float]:
        """Median tick duration (seconds) — registry-backed shim kept for
        callers of the original deque-based API."""
        return self._tick_hist.quantile(0.5)

    def tick_latency_stats(self) -> Dict[str, Optional[float]]:
        """{p50, p95, max} tick durations in seconds (None before the first
        tick); quantiles estimated from histogram buckets, max exact."""
        return {
            "p50": self._tick_hist.quantile(0.5),
            "p95": self._tick_hist.quantile(0.95),
            "max": self._tick_hist.max,
        }
