"""ProtectionService: detect and act on reservation violations.

Reference: tensorhive/core/services/ProtectionService.py:17-131 — every tick,
for each host/chip with processes, look up the current reservation
(Reservation.current_events); processes owned by someone other than the
reservation owner are violations; ``strict_reservations`` additionally flags
processes on chips with *no* reservation (level>1, TensorHiveManager.py:105).
Violations aggregate per intruder, then every configured handler fires.

The TPU twist (BASELINE.json north star): ownership comes from the telemetry
probe's device-holder PIDs (libtpu lock inspection) rather than CUDA context
enumeration — a chip's ``processes`` list in the infra store is exactly the
set of PIDs holding its device node open.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from ...config import Config, get_config
from ...db.models.reservation import Reservation
from ...db.models.user import User
from ...observability import get_registry
from ..handlers.base import ProtectionHandler, Violation
from .base import Service

log = logging.getLogger(__name__)

_VIOLATIONS = get_registry().counter(
    "tpuhive_protection_violations_total",
    "Violation observations (one per intruder per tick while present).",
    labels=("kind",))
_ACTIVE_VIOLATIONS = get_registry().gauge(
    "tpuhive_protection_active_violations",
    "Intruders detected in the most recent protection tick.")
_HANDLER_FAILURES = get_registry().counter(
    "tpuhive_protection_handler_failures_total",
    "Protection handlers that raised while acting on a violation.",
    labels=("handler",))


class ProtectionService(Service):
    def __init__(self, config: Optional[Config] = None,
                 handlers: Optional[List[ProtectionHandler]] = None) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.protection.interval_s)
        self.strict = config.protection.level >= 2
        self.handlers = handlers if handlers is not None else default_handlers(config)
        #: most recent violations, keyed by intruder (API/debug introspection)
        self.last_violations: Dict[str, Violation] = {}

    def do_run(self) -> None:
        assert self.infrastructure_manager is not None, "service not injected"
        violations = self.find_violations()
        self.last_violations = violations
        _ACTIVE_VIOLATIONS.set(len(violations))
        for violation in violations.values():
            _VIOLATIONS.labels(
                kind="unreserved" if violation.unreserved else "reserved").inc()
        for handler in self.handlers:
            handler.begin_tick()
        for violation in violations.values():
            for handler in self.handlers:
                try:
                    handler.trigger_action(violation)
                except Exception:
                    log.exception("handler %s failed", type(handler).__name__)
                    _HANDLER_FAILURES.labels(
                        handler=type(handler).__name__).inc()

    # ------------------------------------------------------------------
    def find_violations(self) -> Dict[str, Violation]:
        """Scan the telemetry snapshot against current reservations
        (reference do_run :80-131). One batched reservation query + one
        batched owner lookup per tick, not one per occupied chip — this runs
        every 2 s on the hot path."""
        violations: Dict[str, Violation] = {}
        nodes = self.infrastructure_manager.all_nodes_with_tpu_processes()
        active = {r.resource_id: r for r in Reservation.current_events()}
        owner_ids = sorted({r.user_id for r in active.values()})
        owners_by_id = {u.id: u for u in User.get_many(owner_ids)} if owner_ids else {}
        for hostname, chips in nodes.items():
            for uid, processes in chips.items():
                if not processes:
                    continue
                reservation = active.get(uid)
                owner_username = None
                if reservation is not None:
                    owner = owners_by_id.get(reservation.user_id)
                    owner_username = owner.username if owner else None
                for proc in processes:
                    proc_user = proc.get("user", "")
                    if not proc_user:
                        continue
                    if reservation is None:
                        if not self.strict:
                            continue  # unreserved use tolerated below level 2
                        unreserved = True
                    else:
                        if proc_user == owner_username:
                            continue
                        unreserved = False
                    violation = violations.setdefault(
                        proc_user, Violation(intruder_username=proc_user)
                    )
                    if uid not in violation.chip_uids:
                        violation.chip_uids.append(uid)
                    if owner_username and owner_username not in violation.owner_usernames:
                        violation.owner_usernames.append(owner_username)
                    violation.pids_by_host.setdefault(hostname, [])
                    if proc["pid"] not in violation.pids_by_host[hostname]:
                        violation.pids_by_host[hostname].append(proc["pid"])
                    violation.unreserved = violation.unreserved or unreserved
        if violations:
            log.info("violations detected: %s",
                     {u: v.all_pids for u, v in violations.items()})
        return violations


def default_handlers(config: Config) -> List[ProtectionHandler]:
    """Handler set per config (reference
    TensorHiveManager.instantiate_services_from_config:71-110: PTY warnings
    always when enabled, email opt-in, kill_processes ∈ {0,1,2})."""
    from ..handlers.email import EmailSendingBehaviour
    from ..handlers.kill import ProcessKillingBehaviour
    from ..handlers.message import MessageSendingBehaviour

    handlers: List[ProtectionHandler] = []
    if config.protection.notify_on_pty:
        handlers.append(MessageSendingBehaviour())
    if config.protection.notify_via_email:
        handlers.append(EmailSendingBehaviour(config.mailbot))
    if config.protection.kill_mode == 1:
        handlers.append(ProcessKillingBehaviour(sudo=False))
    elif config.protection.kill_mode == 2:
        handlers.append(ProcessKillingBehaviour(sudo=True))
    return handlers
