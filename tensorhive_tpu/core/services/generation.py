"""GenerationService: the pump thread of the continuous-batching gateway.

Deliberately thin, like AlertingService: all scheduling/batching logic
lives in :mod:`tensorhive_tpu.serving.engine` (deterministically testable
with a fake clock); this daemon owns the *process lifecycle* — build the
model + engine at boot, install it as the process-wide engine the API
controller reads, and drive ``engine.pump`` every tick. Subclassing
:class:`Service` buys the tick histogram, overrun counters and liveness
stamps, so the serving loop is covered by the ``service_down`` rule and
``/api/readyz`` like every other daemon.

The tick body budgets itself inside the service interval (``pump`` takes a
wall budget and re-checks ``self.stopped``): a saturated engine keeps a
~90% duty cycle without tripping the tick-overrun alert on every tick, and
shutdown never waits on a long generation.

Boot-time failure policy: a configured checkpoint that cannot be served
(missing, unreadable, params shaped for a different preset) must neither
crash the whole daemon NOR silently fall back to random init params — the
service comes up with no engine, records the reason, and the API answers
503 carrying it (docs/SERVING.md "Loading checkpoints").
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Optional

from ...config import Config, get_config
from ...serving import CheckpointLoadError
from .base import Service

log = logging.getLogger(__name__)


class GenerationService(Service):
    def __init__(self, config: Optional[Config] = None,
                 engine: Optional[object] = None) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.generation.interval_s)
        self.generation_config = config.generation
        # ~90% duty cycle: pump inside the interval, leave a sliver for the
        # run-loop's interruptible wait so stop() is honored promptly
        self._pump_budget_s = max(0.001, self.interval_s * 0.9)
        from ... import serving

        if engine is not None:
            self.engine = engine
        else:
            try:
                self.engine = build_engine(config)
            except CheckpointLoadError as exc:
                # the daemon stays up (monitoring/scheduling are unaffected)
                # and the serving plane 503s with the reason — an operator
                # fixing the path re-enables it with a restart, and nothing
                # ever silently serves init params in place of a requested
                # checkpoint
                log.error("generation serving disabled: %s", exc)
                serving.set_unavailable_reason(str(exc))
                self.engine = None
        if self.engine is not None:
            serving.set_engine(self.engine)

    def do_run(self) -> None:
        if self.engine is None:
            return
        self.engine.pump(budget_s=self._pump_budget_s,
                         should_stop=lambda: self.stopped)

    def shutdown(self) -> None:
        # un-publish before stopping so the controller 503s new requests
        # instead of queueing onto a pump that will never run again
        from ... import serving

        if self.engine is not None and serving.get_engine() is self.engine:
            serving.set_engine(None)
        super().shutdown()


def load_checkpoint_params(path: str, model_config):
    """Load train_loop params (orbax, ``{"params", "opt_state"}`` layout —
    train.py::save_checkpoint) for serving: returns ``(step, params)``
    restored to the default single-device placement, which the engine's
    ``device_put`` then moves into the serving-mesh layout (orbax reshards
    on restore anyway — train.py::restore_checkpoint — so the save-time
    topology never constrains where serving runs).

    Raises :class:`~tensorhive_tpu.serving.CheckpointLoadError` — with the
    exact tree/shape mismatches in the message — whenever the checkpoint
    cannot be served as-configured; the caller turns that into a 503
    reason, never a crash and never a silent init-params fallback."""
    import jax

    from ...models.transformer import TransformerLM

    try:
        import orbax.checkpoint as ocp
    except ImportError as exc:  # pragma: no cover - orbax is in the image
        raise CheckpointLoadError(
            f"checkpoint_path is set but orbax is unavailable: {exc}"
        ) from exc
    try:
        with ocp.CheckpointManager(path) as manager:
            step = manager.latest_step()
            if step is None:
                raise CheckpointLoadError(
                    f"no checkpoint steps under {path!r}")
            # template-free PyTreeRestore: the tree layout comes from the
            # checkpoint itself (this loader must read checkpoints for ANY
            # preset to report a shape mismatch instead of crashing on a
            # structure it guessed wrong); a bare restore(step) is rejected
            # by this orbax ("provide a CheckpointArgs subclass")
            restored = manager.restore(step, args=ocp.args.PyTreeRestore())
    except CheckpointLoadError:
        raise
    except Exception as exc:
        raise CheckpointLoadError(
            f"cannot read checkpoint {path!r}: "
            f"{type(exc).__name__}: {exc}") from exc
    params = restored.get("params") if hasattr(restored, "get") else None
    if params is None:
        raise CheckpointLoadError(
            f"checkpoint {path!r} has no 'params' entry — not a "
            "train_loop checkpoint?")

    # shape-validate against the preset BEFORE any device allocation:
    # eval_shape materializes nothing, and the mismatch message names the
    # offending leaves so the 503 is actionable
    expected = jax.eval_shape(
        lambda key: TransformerLM.init(key, model_config),
        jax.random.PRNGKey(0))

    def leaves_by_path(tree):
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        return {jax.tree_util.keystr(kp): leaf for kp, leaf in flat}

    got = leaves_by_path(params)
    want = leaves_by_path(expected)
    problems = []
    for missing in sorted(set(want) - set(got)):
        problems.append(f"{missing} missing")
    for extra in sorted(set(got) - set(want)):
        problems.append(f"{extra} unexpected")
    for key in sorted(set(want) & set(got)):
        if tuple(got[key].shape) != tuple(want[key].shape):
            problems.append(
                f"{key} shape {tuple(got[key].shape)} != expected "
                f"{tuple(want[key].shape)}")
    if problems:
        raise CheckpointLoadError(
            f"checkpoint {path!r} does not fit preset params "
            f"({len(problems)} mismatches): " + "; ".join(problems[:6]))
    return step, params


def build_engine(config: Config):
    """Construct the slot engine from ``[generation_service]`` config and
    warm its executables so the first request never pays a compile.

    Multi-chip serving (docs/SERVING.md): ``mesh_dp``/``mesh_tp`` build a
    serving mesh over the first ``dp*tp`` devices — capacity scales with
    dp (the configured ``slots``/``kv_pages`` are PER DP SHARD, so per-chip
    HBM stays what the operator sized) and per-token work shards over tp.
    The 1x1 default passes ``mesh=None``: byte-identical to the single-chip
    engine, same executables, same compile fingerprints (the rollback
    contract the mesh smoke pins).

    Imports jax lazily: processes with serving disabled must not pay model
    stack import time (instantiate_services_from_config only calls this
    when enabled)."""
    import jax

    from ...models.transformer import PRESETS, TransformerLM
    from ...serving.engine import SlotEngine

    generation = config.generation
    if generation.preset not in PRESETS:
        raise ValueError(
            f"[generation_service] preset {generation.preset!r} unknown; "
            f"choose from {sorted(PRESETS)}")
    if generation.request_ledger_size < 1:
        raise ValueError(
            f"[generation_service] request_ledger_size must be >= 1, got "
            f"{generation.request_ledger_size}")
    # bound the per-request trace ring (GET /api/admin/requests) the engine
    # will write into — sized here so the knob lives with the rest of the
    # serving config
    from ...observability import get_request_ledger

    get_request_ledger().set_capacity(generation.request_ledger_size)
    mesh_dp, mesh_tp = int(generation.mesh_dp), int(generation.mesh_tp)
    if mesh_dp < 1 or mesh_tp < 1:
        raise ValueError(
            f"[generation_service] mesh_dp/mesh_tp must be >= 1, got "
            f"{mesh_dp}/{mesh_tp}")
    mesh = None
    if mesh_dp * mesh_tp > 1:
        from ...parallel.mesh import serving_mesh

        mesh = serving_mesh(dp=mesh_dp, tp=mesh_tp)
    model_config = PRESETS[generation.preset]
    max_len = generation.max_len or model_config.max_seq_len
    model_config = dataclasses.replace(
        model_config,
        max_seq_len=max(max_len, model_config.max_seq_len),
        use_flash=generation.use_flash)
    if generation.checkpoint_path:
        step, params = load_checkpoint_params(
            generation.checkpoint_path, model_config)
        log.info("serving checkpoint %s step %d", generation.checkpoint_path,
                 step)
        if mesh is None:
            # no mesh layout to target — commit the host arrays once so the
            # executables never re-transfer them per dispatch
            params = jax.tree_util.tree_map(jax.device_put, params)
    else:
        # random init: the gateway serves whatever params the process holds
        params = TransformerLM.init(jax.random.PRNGKey(0), model_config)
    engine = SlotEngine(
        params, model_config,
        slots=generation.slots * mesh_dp,
        max_len=max_len,
        paged=generation.paged,
        page_size=generation.page_size,
        kv_pages=generation.kv_pages * mesh_dp,
        paged_kernel=generation.paged_kernel,
        prefix_cache=generation.prefix_cache,
        prefix_min_tokens=generation.prefix_min_tokens,
        prefill_chunk_tokens=generation.prefill_chunk_tokens,
        speculative=generation.speculative,
        draft_preset=generation.draft_preset,
        draft_layers=generation.draft_layers,
        spec_tokens=generation.spec_tokens,
        mesh=mesh,
        queue_depth=generation.queue_depth,
        top_k=generation.top_k or None,
        eos_token=None if generation.eos_token < 0 else generation.eos_token,
        max_new_tokens_cap=generation.max_new_tokens,
        max_concurrent_per_user=generation.max_concurrent_per_user,
    )
    engine.warmup(prompt_lens=(16, max_len // 2))
    log.info("generation engine ready: preset=%s slots=%d max_len=%d "
             "queue_depth=%d mesh=%s devices=%d", generation.preset,
             engine.capacity, max_len, generation.queue_depth,
             engine.mesh_shape, engine.num_devices)
    return engine
