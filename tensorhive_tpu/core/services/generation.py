"""GenerationService: the pump thread of the continuous-batching gateway.

Deliberately thin, like AlertingService: all scheduling/batching logic
lives in :mod:`tensorhive_tpu.serving.engine` (deterministically testable
with a fake clock); this daemon owns the *process lifecycle* — build the
model + engine at boot, install it as the process-wide engine the API
controller reads, and drive ``engine.pump`` every tick. Subclassing
:class:`Service` buys the tick histogram, overrun counters and liveness
stamps, so the serving loop is covered by the ``service_down`` rule and
``/api/readyz`` like every other daemon.

The tick body budgets itself inside the service interval (``pump`` takes a
wall budget and re-checks ``self.stopped``): a saturated engine keeps a
~90% duty cycle without tripping the tick-overrun alert on every tick, and
shutdown never waits on a long generation.

Boot-time failure policy: a configured checkpoint that cannot be served
(missing, unreadable, params shaped for a different preset) must neither
crash the whole daemon NOR silently fall back to random init params — the
service comes up with no engine, records the reason, and the API answers
503 carrying it (docs/SERVING.md "Loading checkpoints").

Run-time failure policy (the engine supervisor, docs/ROBUSTNESS.md
"Serving data plane"): a pump tick that raises is classified transient vs
fatal (``serving/faults.py::classify_failure`` — fatal by default, because
a failure inside a dispatch may have consumed the donated KV cache).
Transient failures retry the tick against the SAME engine with bounded
exponential backoff; a fatal failure FAILS FAST — every in-flight stream
gets a terminal ``{"error": ...}`` chunk and an ``outcome=failed`` ledger
row, streams never hang — then the engine is rebuilt (fresh cache,
checkpoint reload, same config) under a capped restart budget. Exhausting
the budget inside the window trips a crash-loop breaker: the plane
un-publishes with a 503 reason (exactly like the checkpoint-load path) and
one probe rebuild is allowed per cooldown. ``shutdown()`` rides the drain
path: admission stops with an honest Retry-After, in-flight requests get
``drain_timeout_s`` to finish, stragglers are failed fast — a restart is
never a silent EOF.
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, List, Optional

from ...config import Config, get_config
from ...observability import get_registry
from ...observability.accounting import get_tenant_meter
from ...serving import CheckpointLoadError
from ...serving.faults import TRANSIENT, classify_failure
from .base import Service

log = logging.getLogger(__name__)

_ENGINE_RESTARTS = get_registry().counter(
    "tpuhive_generate_engine_restarts_total",
    "Successful serving-engine rebuilds after a fatal data-plane failure "
    "(fresh cache + checkpoint reload; docs/ROBUSTNESS.md 'Serving data "
    "plane').")
_STEP_FAILURES = get_registry().counter(
    "tpuhive_generate_step_failures_total",
    "Generation pump failures by classified kind: transient (tick retried "
    "against the same engine) or fatal (fail-fast + engine rebuild).",
    labels=("kind",))


class GenerationService(Service):
    def __init__(self, config: Optional[Config] = None,
                 engine: Optional[object] = None,
                 engine_factory: Optional[Callable[[], object]] = None,
                 ) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.generation.interval_s)
        self.generation_config = config.generation
        #: where fatal failures leave flight-recorder crash dumps
        self._flightrec_dir = str(config.flightrec_dir)
        # ~90% duty cycle: pump inside the interval, leave a sliver for the
        # run-loop's interruptible wait so stop() is honored promptly
        self._pump_budget_s = max(0.001, self.interval_s * 0.9)
        # -- supervisor state (docs/ROBUSTNESS.md "Serving data plane") ----
        #: consecutive transient pump failures in the current incident
        self._transient_streak = 0
        #: monotonic stamps of rebuild attempts inside the sliding window
        self._restart_attempts: List[float] = []
        #: crash-loop breaker: no rebuilds before this monotonic stamp
        #: (None = closed); set when the budget is exhausted in-window
        self._breaker_open_until: Optional[float] = None
        self._engine_factory = engine_factory
        from ... import serving

        # a fresh supervisor owns the plane from a clean slate: the restart
        # counter and the crash-loop flag describe THIS supervisor's era
        serving.update_serving_state(supervisor_active=True, restarts=0,
                                     crash_loop=False, retry_after_s=None)
        if engine is not None:
            self.engine = engine
        else:
            if self._engine_factory is None:
                self._engine_factory = lambda: build_engine(config)
            try:
                self.engine = self._engine_factory()
            except CheckpointLoadError as exc:
                # the daemon stays up (monitoring/scheduling are unaffected)
                # and the serving plane 503s with the reason — an operator
                # fixing the path re-enables it with a restart, and nothing
                # ever silently serves init params in place of a requested
                # checkpoint
                log.error("generation serving disabled: %s", exc)
                serving.set_unavailable_reason(str(exc))
                self.engine = None
        if self.engine is not None:
            serving.set_engine(self.engine)

    def do_run(self) -> None:
        if self.engine is None:
            self._maybe_rebuild()
            return
        try:
            self.engine.pump(budget_s=self._pump_budget_s,
                             should_stop=lambda: self.stopped)
            self._transient_streak = 0
        except Exception as exc:    # noqa: BLE001 - the supervisor's seam
            self._handle_pump_failure(exc)

    # -- supervisor --------------------------------------------------------
    def _handle_pump_failure(self, exc: BaseException) -> None:
        """Classify one pump failure and act: transient → bounded-backoff
        retry against the same engine; fatal (or transient budget spent) →
        fail-fast every in-flight stream, then rebuild under the restart
        budget."""
        kind = classify_failure(exc)
        _STEP_FAILURES.labels(kind=kind).inc()
        generation = self.generation_config
        if (kind == TRANSIENT
                and self._transient_streak < generation.transient_retries):
            self._transient_streak += 1
            backoff = (generation.transient_backoff_s
                       * 2 ** (self._transient_streak - 1))
            log.warning(
                "generation pump transient failure "
                "(retry %d/%d after %.3fs): %s",
                self._transient_streak, generation.transient_retries,
                backoff, exc)
            if backoff > 0:
                self.wait(backoff)      # interruptible by shutdown
            return
        self._transient_streak = 0
        log.error("generation pump fatal failure (%s): failing fast and "
                  "rebuilding the engine", type(exc).__name__, exc_info=exc)
        self._fail_fast(exc)
        self._maybe_rebuild()

    def _fail_fast(self, exc: BaseException) -> None:
        """Un-publish the dead engine and finish every in-flight request
        with a terminal error chunk + ``outcome=failed`` ledger row —
        streams must NEVER hang on a dead device."""
        from ... import serving

        engine = self.engine
        self.engine = None
        if serving.get_engine() is engine:
            serving.set_engine(None)
        serving.set_unavailable_reason(
            f"serving engine failed ({type(exc).__name__}: {exc}); "
            "restart in progress")
        serving.update_serving_state(
            retry_after_s=max(1.0, 2 * self.interval_s))
        # crash dump BEFORE failing the streams: the in-flight ledger rows
        # must show what was actually running when the fault hit
        self._write_crash_dump(engine, exc)
        failed = engine.fail_all_inflight(
            f"engine fault ({type(exc).__name__}: {exc}); the engine is "
            "restarting — retry the request")
        if failed:
            log.warning("failed fast %d in-flight generation request(s)",
                        failed)

    def _write_crash_dump(self, engine, exc: BaseException) -> None:
        """Best-effort post-mortem: snapshot the flight-recorder ring, the
        in-flight ledger rows and the firing alerts into
        ``{config_dir}/flightrec/`` (docs/OBSERVABILITY.md "History, SLOs
        & flight recorder"). Quietly a no-op when the recorder is off, and
        NEVER allowed to block the fail-fast path."""
        recorder = getattr(engine, "flight_recorder", None)
        if recorder is None:
            return
        try:
            from ...observability import get_request_ledger
            from ...observability.alerts import get_alert_engine
            from ...serving.flight_recorder import write_crash_dump

            path = write_crash_dump(
                self._flightrec_dir,
                reason=f"{type(exc).__name__}: {exc}",
                recorder=recorder,
                inflight=get_request_ledger().in_flight(),
                alerts=get_alert_engine().firing(),
                max_dumps=self.generation_config.flightrec_dumps)
            log.error("flight-recorder crash dump written: %s", path)
        except Exception:   # noqa: BLE001 - the post-mortem must never
            # out-crash the recovery
            log.exception("flight-recorder crash dump failed")

    def _maybe_rebuild(self) -> None:
        """Attempt an engine rebuild, rate-limited by the restart budget:
        at most ``restart_budget`` attempts per ``restart_window_s``.
        Exhausting it trips the crash-loop breaker — the plane stays
        un-published with the reason until ``restart_cooldown_s`` elapses,
        then ONE probe era (a fresh budget) is allowed, exactly like the
        transport breaker's half-open state."""
        from ... import serving

        if self._engine_factory is None:
            return      # injected engine without a factory: nothing to do
        generation = self.generation_config
        now = time.monotonic()
        if self._breaker_open_until is not None:
            if now < self._breaker_open_until:
                return
            self._breaker_open_until = None
            self._restart_attempts.clear()      # half-open: fresh budget
        window = float(generation.restart_window_s)
        self._restart_attempts = [stamp for stamp in self._restart_attempts
                                  if now - stamp < window]
        if len(self._restart_attempts) >= generation.restart_budget:
            cooldown = float(generation.restart_cooldown_s)
            self._breaker_open_until = now + cooldown
            reason = (f"serving engine crash loop: "
                      f"{len(self._restart_attempts)} restarts in "
                      f"{window:g}s; breaker open, next rebuild attempt in "
                      f"{cooldown:g}s")
            log.error(reason)
            serving.set_unavailable_reason(reason)
            serving.update_serving_state(crash_loop=True,
                                         retry_after_s=cooldown)
            return
        self._restart_attempts.append(now)
        try:
            engine = self._engine_factory()
        except Exception as exc:    # noqa: BLE001 - rebuild failures are
            # the crash-loop signal, not a reason to kill the daemon
            log.error("generation engine rebuild failed: %s", exc,
                      exc_info=True)
            serving.set_unavailable_reason(
                f"engine rebuild failed ({type(exc).__name__}: {exc}); "
                "retrying")
            return
        self.engine = engine
        _ENGINE_RESTARTS.inc()
        restarts = serving.get_serving_state()["restarts"] + 1
        serving.update_serving_state(restarts=restarts)
        # publishing clears the unavailability reason, the crash-loop flag
        # and the Retry-After hint — the engine IS the recovery signal
        serving.set_engine(engine)
        log.info("generation engine restored (rebuild #%d)", restarts)

    def shutdown(self) -> None:
        """Stop via the drain path: admission closes (503 + Retry-After at
        the edge) while in-flight requests get ``drain_timeout_s`` to
        finish; stragglers are failed fast with a terminal chunk — a
        restart never leaves a stream on a silent EOF."""
        from ... import serving

        engine = self.engine
        if engine is not None:
            engine.drain()
            deadline = time.monotonic() + max(
                0.0, float(self.generation_config.drain_timeout_s))
            while engine.has_work() and time.monotonic() < deadline:
                if self.is_alive():
                    # the pump thread is live and keeps draining; just wait
                    time.sleep(min(self.interval_s, 0.05))
                else:
                    # no pump running (pre-start shutdown, tests): drive
                    # the drain ourselves
                    engine.pump(budget_s=self._pump_budget_s)
            if engine.has_work():
                failed = engine.fail_all_inflight(
                    "server shutting down: the drain timeout expired "
                    "before this request finished — retry")
                log.warning("drain timeout: failed fast %d in-flight "
                            "generation request(s)", failed)
        # un-publish before stopping the pump so the controller 503s new
        # requests instead of queueing onto a pump that will never run
        if engine is not None and serving.get_engine() is engine:
            serving.set_engine(None)
        serving.update_serving_state(supervisor_active=False,
                                     crash_loop=False, retry_after_s=None)
        super().shutdown()


def load_checkpoint_params(path: str, model_config):
    """Load train_loop params (orbax, ``{"params", "opt_state"}`` layout —
    train.py::save_checkpoint) for serving: returns ``(step, params)``
    restored to the default single-device placement, which the engine's
    ``device_put`` then moves into the serving-mesh layout (orbax reshards
    on restore anyway — train.py::restore_checkpoint — so the save-time
    topology never constrains where serving runs).

    Raises :class:`~tensorhive_tpu.serving.CheckpointLoadError` — with the
    exact tree/shape mismatches in the message — whenever the checkpoint
    cannot be served as-configured; the caller turns that into a 503
    reason, never a crash and never a silent init-params fallback."""
    import jax

    from ...models.transformer import TransformerLM

    try:
        import orbax.checkpoint as ocp
    except ImportError as exc:  # pragma: no cover - orbax is in the image
        raise CheckpointLoadError(
            f"checkpoint_path is set but orbax is unavailable: {exc}"
        ) from exc
    try:
        with ocp.CheckpointManager(path) as manager:
            step = manager.latest_step()
            if step is None:
                raise CheckpointLoadError(
                    f"no checkpoint steps under {path!r}")
            # template-free PyTreeRestore: the tree layout comes from the
            # checkpoint itself (this loader must read checkpoints for ANY
            # preset to report a shape mismatch instead of crashing on a
            # structure it guessed wrong); a bare restore(step) is rejected
            # by this orbax ("provide a CheckpointArgs subclass")
            restored = manager.restore(step, args=ocp.args.PyTreeRestore())
    except CheckpointLoadError:
        raise
    except Exception as exc:
        raise CheckpointLoadError(
            f"cannot read checkpoint {path!r}: "
            f"{type(exc).__name__}: {exc}") from exc
    params = restored.get("params") if hasattr(restored, "get") else None
    if params is None:
        raise CheckpointLoadError(
            f"checkpoint {path!r} has no 'params' entry — not a "
            "train_loop checkpoint?")

    # shape-validate against the preset BEFORE any device allocation:
    # eval_shape materializes nothing, and the mismatch message names the
    # offending leaves so the 503 is actionable
    expected = jax.eval_shape(
        lambda key: TransformerLM.init(key, model_config),
        jax.random.PRNGKey(0))

    def leaves_by_path(tree):
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        return {jax.tree_util.keystr(kp): leaf for kp, leaf in flat}

    got = leaves_by_path(params)
    want = leaves_by_path(expected)
    problems = []
    for missing in sorted(set(want) - set(got)):
        problems.append(f"{missing} missing")
    for extra in sorted(set(got) - set(want)):
        problems.append(f"{extra} unexpected")
    for key in sorted(set(want) & set(got)):
        if tuple(got[key].shape) != tuple(want[key].shape):
            problems.append(
                f"{key} shape {tuple(got[key].shape)} != expected "
                f"{tuple(want[key].shape)}")
    if problems:
        raise CheckpointLoadError(
            f"checkpoint {path!r} does not fit preset params "
            f"({len(problems)} mismatches): " + "; ".join(problems[:6]))
    return step, params


def build_flight_recorder(generation):
    """Per-tick black box for the engine (docs/OBSERVABILITY.md "History,
    SLOs & flight recorder"); None — the byte-identical unrecorded step()
    path — when ``flight_recorder`` is off."""
    if not generation.flight_recorder:
        return None
    if generation.flightrec_ticks < 1:
        raise ValueError(
            f"[generation_service] flightrec_ticks must be >= 1, got "
            f"{generation.flightrec_ticks}")
    from ...serving.flight_recorder import FlightRecorder

    return FlightRecorder(capacity=generation.flightrec_ticks)


def build_engine(config: Config):
    """Construct the slot engine from ``[generation_service]`` config and
    warm its executables so the first request never pays a compile.

    Multi-chip serving (docs/SERVING.md): ``mesh_dp``/``mesh_tp`` build a
    serving mesh over the first ``dp*tp`` devices — capacity scales with
    dp (the configured ``slots``/``kv_pages`` are PER DP SHARD, so per-chip
    HBM stays what the operator sized) and per-token work shards over tp.
    The 1x1 default passes ``mesh=None``: byte-identical to the single-chip
    engine, same executables, same compile fingerprints (the rollback
    contract the mesh smoke pins).

    Imports jax lazily: processes with serving disabled must not pay model
    stack import time (instantiate_services_from_config only calls this
    when enabled)."""
    import jax

    from ...models.transformer import PRESETS, TransformerLM
    from ...serving.engine import SlotEngine

    generation = config.generation
    if generation.preset not in PRESETS:
        raise ValueError(
            f"[generation_service] preset {generation.preset!r} unknown; "
            f"choose from {sorted(PRESETS)}")
    if generation.request_ledger_size < 1:
        raise ValueError(
            f"[generation_service] request_ledger_size must be >= 1, got "
            f"{generation.request_ledger_size}")
    # bound the per-request trace ring (GET /api/admin/requests) the engine
    # will write into — sized here so the knob lives with the rest of the
    # serving config
    from ...observability import get_request_ledger

    get_request_ledger().set_capacity(generation.request_ledger_size)
    mesh_dp, mesh_tp = int(generation.mesh_dp), int(generation.mesh_tp)
    if mesh_dp < 1 or mesh_tp < 1:
        raise ValueError(
            f"[generation_service] mesh_dp/mesh_tp must be >= 1, got "
            f"{mesh_dp}/{mesh_tp}")
    mesh = None
    if mesh_dp * mesh_tp > 1:
        from ...parallel.mesh import serving_mesh

        mesh = serving_mesh(dp=mesh_dp, tp=mesh_tp)
    model_config = PRESETS[generation.preset]
    max_len = generation.max_len or model_config.max_seq_len
    model_config = dataclasses.replace(
        model_config,
        max_seq_len=max(max_len, model_config.max_seq_len),
        use_flash=generation.use_flash)
    if generation.checkpoint_path:
        step, params = load_checkpoint_params(
            generation.checkpoint_path, model_config)
        log.info("serving checkpoint %s step %d", generation.checkpoint_path,
                 step)
        if mesh is None:
            # no mesh layout to target — commit the host arrays once so the
            # executables never re-transfer them per dispatch
            params = jax.tree_util.tree_map(jax.device_put, params)
    else:
        # random init: the gateway serves whatever params the process holds
        params = TransformerLM.init(jax.random.PRNGKey(0), model_config)
    engine = SlotEngine(
        params, model_config,
        slots=generation.slots * mesh_dp,
        max_len=max_len,
        paged=generation.paged,
        page_size=generation.page_size,
        kv_pages=generation.kv_pages * mesh_dp,
        paged_kernel=generation.paged_kernel,
        kv_quant=generation.kv_quant,
        prefix_cache=generation.prefix_cache,
        prefix_min_tokens=generation.prefix_min_tokens,
        prefill_chunk_tokens=generation.prefill_chunk_tokens,
        host_kv_bytes=generation.host_kv_bytes,
        speculative=generation.speculative,
        draft_preset=generation.draft_preset,
        draft_layers=generation.draft_layers,
        spec_tokens=generation.spec_tokens,
        mesh=mesh,
        default_deadline_s=generation.default_deadline_s,
        max_deadline_s=generation.max_deadline_s,
        queue_depth=generation.queue_depth,
        top_k=generation.top_k or None,
        eos_token=None if generation.eos_token < 0 else generation.eos_token,
        max_new_tokens_cap=generation.max_new_tokens,
        max_concurrent_per_user=generation.max_concurrent_per_user,
        flight_recorder=build_flight_recorder(generation),
        tenant_meter=get_tenant_meter(),
    )
    engine.warmup(prompt_lens=(16, max_len // 2))
    log.info("generation engine ready: preset=%s slots=%d max_len=%d "
             "queue_depth=%d mesh=%s devices=%d", generation.preset,
             engine.capacity, max_len, generation.queue_depth,
             engine.mesh_shape, engine.num_devices)
    return engine
