"""GenerationService: the pump thread of the continuous-batching gateway.

Deliberately thin, like AlertingService: all scheduling/batching logic
lives in :mod:`tensorhive_tpu.serving.engine` (deterministically testable
with a fake clock); this daemon owns the *process lifecycle* — build the
model + engine at boot, install it as the process-wide engine the API
controller reads, and drive ``engine.pump`` every tick. Subclassing
:class:`Service` buys the tick histogram, overrun counters and liveness
stamps, so the serving loop is covered by the ``service_down`` rule and
``/api/readyz`` like every other daemon.

The tick body budgets itself inside the service interval (``pump`` takes a
wall budget and re-checks ``self.stopped``): a saturated engine keeps a
~90% duty cycle without tripping the tick-overrun alert on every tick, and
shutdown never waits on a long generation.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Optional

from ...config import Config, get_config
from .base import Service

log = logging.getLogger(__name__)


class GenerationService(Service):
    def __init__(self, config: Optional[Config] = None,
                 engine: Optional[object] = None) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.generation.interval_s)
        self.generation_config = config.generation
        # ~90% duty cycle: pump inside the interval, leave a sliver for the
        # run-loop's interruptible wait so stop() is honored promptly
        self._pump_budget_s = max(0.001, self.interval_s * 0.9)
        self.engine = engine if engine is not None else build_engine(config)
        from ... import serving

        serving.set_engine(self.engine)

    def do_run(self) -> None:
        self.engine.pump(budget_s=self._pump_budget_s,
                         should_stop=lambda: self.stopped)

    def shutdown(self) -> None:
        # un-publish before stopping so the controller 503s new requests
        # instead of queueing onto a pump that will never run again
        from ... import serving

        if serving.get_engine() is self.engine:
            serving.set_engine(None)
        super().shutdown()


def build_engine(config: Config):
    """Construct the slot engine from ``[generation_service]`` config and
    warm its executables so the first request never pays a compile.

    Imports jax lazily: processes with serving disabled must not pay model
    stack import time (instantiate_services_from_config only calls this
    when enabled)."""
    import jax

    from ...models.transformer import PRESETS, TransformerLM
    from ...serving.engine import SlotEngine

    generation = config.generation
    if generation.preset not in PRESETS:
        raise ValueError(
            f"[generation_service] preset {generation.preset!r} unknown; "
            f"choose from {sorted(PRESETS)}")
    model_config = PRESETS[generation.preset]
    max_len = generation.max_len or model_config.max_seq_len
    model_config = dataclasses.replace(
        model_config,
        max_seq_len=max(max_len, model_config.max_seq_len),
        use_flash=generation.use_flash)
    # random init: the gateway serves whatever params the process holds —
    # checkpoint loading is the job template / train_loop story, and the
    # serving plane is checkpoint-agnostic by design
    params = TransformerLM.init(jax.random.PRNGKey(0), model_config)
    engine = SlotEngine(
        params, model_config,
        slots=generation.slots,
        max_len=max_len,
        paged=generation.paged,
        page_size=generation.page_size,
        kv_pages=generation.kv_pages,
        paged_kernel=generation.paged_kernel,
        queue_depth=generation.queue_depth,
        top_k=generation.top_k or None,
        eos_token=None if generation.eos_token < 0 else generation.eos_token,
        max_new_tokens_cap=generation.max_new_tokens,
        max_concurrent_per_user=generation.max_concurrent_per_user,
    )
    engine.warmup(prompt_lens=(16, max_len // 2))
    log.info("generation engine ready: preset=%s slots=%d max_len=%d "
             "queue_depth=%d", generation.preset, generation.slots, max_len,
             generation.queue_depth)
    return engine
