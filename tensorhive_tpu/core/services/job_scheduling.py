"""JobSchedulingService: timed starts/stops + queue draining + preemption.

Reference: tensorhive/core/services/JobSchedulingService.py:23-297 — each
tick (default 30 s): run user-timed jobs whose ``start_at`` arrived
(``execute_scheduled`` :134), else drain the queue via the Scheduler
(``execute_queued`` :197), stop jobs whose ``stop_at`` passed with a
graceful→SIGKILL escalation tracked in ``stubborn_job_ids`` (:210-252), and
preempt queue-launched jobs whose chips acquired a reservation or foreign
process (``sync_running_from_queue`` :254-283).
"""
from __future__ import annotations

import logging
from datetime import timedelta
from typing import Dict, Optional, Set

from ...config import Config, get_config
from ...db.models.job import Job, JobStatus
from ...db.models.reservation import Reservation
from ...db.models.user import User
from ...observability import get_registry, get_tracer
from ...utils.exceptions import NotFoundError, TpuHiveError
from ...utils.timeutils import minutes_between, utcnow
from ..managers.infrastructure import LEASE_DEREGISTERED, LEASE_UNREACHABLE
from ..scheduling import GreedyScheduler, Scheduler, expand_to_slice_uids
from .base import Service

# imported at module scope (not inside tick methods): lazy imports on the
# service thread race the main thread's own first import of the controller
# chain (werkzeug) during boot, corrupting the partially-initialized module
from ...controllers.job import business_execute, business_stop  # noqa: E402

log = logging.getLogger(__name__)

_SPAWNS = get_registry().counter(
    "tpuhive_jobs_spawned_total",
    "Jobs the scheduling service started, by trigger (scheduled, queued).",
    labels=("trigger",))
_SPAWN_FAILURES = get_registry().counter(
    "tpuhive_job_spawn_failures_total",
    "Job starts that failed, by trigger.", labels=("trigger",))
_STOP_ESCALATIONS = get_registry().counter(
    "tpuhive_job_stop_escalations_total",
    "Jobs that ignored a graceful stop and were marked for SIGKILL.")
_PREEMPTIONS = get_registry().counter(
    "tpuhive_job_preemptions_total",
    "Queue-launched jobs preempted for a reservation or foreign process.")
_DISPLACEMENTS = get_registry().counter(
    "tpuhive_job_displacements_total",
    "Running jobs stopped because their host is draining or its membership "
    "lease expired (docs/ROBUSTNESS.md).")


def _spawn_job(job: Job, trigger: str) -> bool:
    """Start one job with spawn accounting + a traced span; returns whether
    the start succeeded (failures are logged, counted, and swallowed so one
    bad job never stalls the tick — reference behaviour preserved)."""
    with get_tracer().span(f"job.spawn.{job.id}", kind="job",
                           job_id=job.id, trigger=trigger) as span:
        try:
            log.info("starting %s job %d (%s)", trigger, job.id, job.name)
            business_execute(job.id)
        except TpuHiveError as exc:
            log.warning("%s job %d failed to start: %s", trigger, job.id, exc)
            _SPAWN_FAILURES.labels(trigger=trigger).inc()
            span.status = "error"
            return False
    _SPAWNS.labels(trigger=trigger).inc()
    return True


class JobSchedulingService(Service):
    def __init__(self, config: Optional[Config] = None,
                 scheduler: Optional[Scheduler] = None) -> None:
        config = config or get_config()
        super().__init__(interval_s=config.job_scheduling.interval_s)
        self.stop_attempts_after = timedelta(
            minutes=config.job_scheduling.stop_attempts_after_mins
        )
        self.required_free_minutes = config.job_scheduling.schedule_queued_when_free_mins
        self.scheduler = scheduler or GreedyScheduler()
        #: jobs that ignored a graceful stop; next attempt escalates
        #: (reference stubborn_job_ids, JobSchedulingService.py:32-36)
        self.stubborn_job_ids: Set[int] = set()
        #: first stop attempt per job, for the give-up window
        self._stop_first_attempt: Dict[int, object] = {}

    def do_run(self) -> None:
        now = utcnow()
        started_any = self.execute_scheduled(now)
        if not started_any:
            self.execute_queued(now)
        self.stop_scheduled(now)
        self.sync_running_from_queue(now)
        self.stop_displaced_jobs(now)

    # -- timed starts (reference :134-171) ----------------------------------
    def execute_scheduled(self, now) -> bool:
        started = False
        for job in Job.find_scheduled_to_start(now):
            if self._job_would_interfere(job, now):
                log.info("delaying scheduled job %d: resources busy/reserved", job.id)
                continue
            started = _spawn_job(job, "scheduled") or started
        return started

    # -- queue draining (reference :197-208) --------------------------------
    def execute_queued(self, now) -> None:
        queue = [job for job in Job.get_job_queue()
                 if not self._has_foreign_process(job)]
        if not queue:
            return
        for job in self.scheduler.schedule_jobs(queue, self.required_free_minutes,
                                                at=now,
                                                eligible_hosts=self._eligible_hosts_resolver()):
            _spawn_job(job, "queued")

    # -- timed stops with escalation (reference :210-252) -------------------
    def stop_scheduled(self, now) -> None:
        for job in Job.find_scheduled_to_stop(now):
            self.stop_with_grace(job, now)

    def stop_with_grace(self, job: Job, now) -> None:
        job_id = job.id
        first_attempt = self._stop_first_attempt.setdefault(job_id, now)
        try:
            if job_id in self.stubborn_job_ids:
                log.warning("job %d ignored graceful stop; killing", job_id)
                business_stop(job_id, gracefully=False)
            else:
                business_stop(job_id, gracefully=True)
        except TpuHiveError as exc:
            log.warning("stopping job %d failed: %s", job_id, exc)
        try:
            job = Job.get(job_id)
        except NotFoundError:
            # the row vanished mid-stop (deleted via the API between
            # business_stop and the re-read): there is nothing left to stop —
            # clean up the escalation bookkeeping that used to leak (and the
            # raise used to crash the whole tick, stalling every other job)
            log.info("job %d deleted during stop; treating as stopped", job_id)
            self.stubborn_job_ids.discard(job_id)
            self._stop_first_attempt.pop(job_id, None)
            return
        if job.status is JobStatus.running:
            if (now - first_attempt >= self.stop_attempts_after
                    and job.id not in self.stubborn_job_ids):
                self.stubborn_job_ids.add(job.id)
                _STOP_ESCALATIONS.inc()
        else:
            self.stubborn_job_ids.discard(job.id)
            self._stop_first_attempt.pop(job.id, None)

    # -- preemption of queue-launched jobs (reference :254-283) -------------
    def sync_running_from_queue(self, now) -> None:
        for job in Job.get_jobs_running_from_queue():
            job.synchronize_status()
            job = Job.get(job.id)
            if job.status is not JobStatus.running:
                continue
            if self._reservation_imminent(job, now) or self._has_foreign_process(job):
                log.info("preempting queued job %d: reservation/foreign process", job.id)
                _PREEMPTIONS.inc()
                self.stop_with_grace(job, now)

    # -- membership displacement (docs/ROBUSTNESS.md "Host membership &
    # leases") ---------------------------------------------------------------
    def stop_displaced_jobs(self, now) -> None:
        """Reap running jobs on hosts that can no longer carry work: admin
        drain (graceful stop, reservation left intact so resume picks it
        back up) or an expired/deregistered membership lease (the host was
        preempted or fell silent — the processes may already be dead, and
        stop_with_grace swallows the transport errors so a vanished host can
        never crash the scheduling tick)."""
        if self.infrastructure_manager is None:
            return
        displaced = {
            hostname for hostname, lease
            in self.infrastructure_manager.host_leases().items()
            if lease["draining"]
            or lease["state"] in (LEASE_UNREACHABLE, LEASE_DEREGISTERED)}
        if not displaced:
            return
        for job in Job.where("_status = ?", [JobStatus.running.value]):
            job_hosts = {task.hostname for task in job.tasks}
            if not (job_hosts & displaced):
                continue
            log.info("stopping displaced job %d: host(s) %s draining or lease "
                     "expired", job.id, sorted(job_hosts & displaced))
            _DISPLACEMENTS.inc()
            self.stop_with_grace(job, now)

    # -- helpers -------------------------------------------------------------
    def _reservation_imminent(self, job: Job, now) -> bool:
        """A reservation by someone else is active or starts within the
        required-free window on any chip the job holds — or on any sibling
        chip of a slice the job runs on (one SPMD program per slice: a
        foreign reservation anywhere on it preempts, core/scheduling.py
        expand_to_slice_uids)."""
        for uid in expand_to_slice_uids(job.chip_uids):
            current = Reservation.current_for_resource(uid, at=now)
            if current is not None and current.user_id != job.user_id:
                return True
            for upcoming in Reservation.upcoming_events_for_resource(uid, at=now):
                if (upcoming.user_id != job.user_id
                        and minutes_between(now, upcoming.start) < self.required_free_minutes):
                    return True
        return False

    def _job_would_interfere(self, job: Job, now) -> bool:
        """Timed-start gate: chips must be unreserved (by others) and free of
        foreign processes (reference check_if_resources_available_for_job +
        interferes_with_reservations, :106-132)."""
        return self._reservation_imminent(job, now) or self._has_foreign_process(job)

    def _eligible_hosts_resolver(self):
        """Per-tick resolver: hosts a job's owner may launch on — known to
        the monitoring infrastructure and, after restriction filtering,
        carrying at least one permitted chip (a host reporting zero chips
        stays eligible for CPU-only work). Reference
        get_hosts_with_gpus_eligible_for_jobs →
        User.filter_infrastructure_by_user_restrictions
        (JobSchedulingService.py:174-195). Returns None (= unrestricted)
        when no infrastructure manager is wired, e.g. in bare unit tests.

        The infra snapshot (a deepcopy under the RWLock) is taken once per
        schedule pass and eligibility is memoized per owner, so N queued
        jobs don't cost N snapshots + N restriction-query sets.

        Host-health gating: the snapshot now RETAINS last-known-good data
        for degraded/unreachable hosts, so presence of a ``TPU`` subtree no
        longer implies the host is alive — nodes whose HEALTH state is
        degraded or unreachable are excluded, as are hosts whose transport
        circuit breaker is open (a queued job must never spawn onto a node
        the control plane cannot even reach).

        Membership gating (docs/ROBUSTNESS.md "Host membership & leases"):
        a host whose LEASE is not effectively live — draining, suspect,
        expired or deregistered — takes no new work either."""
        if self.infrastructure_manager is None:
            return None
        open_circuit = (
            set(self.transport_manager.open_circuit_hosts())
            if self.transport_manager is not None else set())
        host_chips = {
            hostname: set(node["TPU"])
            for hostname, node in self.infrastructure_manager.infrastructure.items()
            if "TPU" in node  # absent = never reported
            and node.get("HEALTH", {}).get("state") not in ("degraded", "unreachable")
            and node.get("LEASE", {}).get("effective", "live") == "live"
            and hostname not in open_circuit
        }
        by_owner: Dict[int, Set[str]] = {}

        def eligible_hosts(job: Job) -> Set[str]:
            if job.user_id not in by_owner:
                try:
                    allowed = User.get(job.user_id).allowed_resource_uids()
                except NotFoundError:
                    allowed = set()  # orphaned job: never eligible
                by_owner[job.user_id] = {
                    hostname for hostname, chips in host_chips.items()
                    if allowed is None or not chips or (chips & allowed)
                }
            return by_owner[job.user_id]

        return eligible_hosts

    def _has_foreign_process(self, job: Job) -> bool:
        if self.infrastructure_manager is None:
            return False
        try:
            owner = User.get(job.user_id).username
        except NotFoundError:
            return False
        for uid in expand_to_slice_uids(job.chip_uids):
            hostname = self.infrastructure_manager.find_chip_hostname(uid)
            if hostname is None:
                continue
            for proc_uid, procs in self.infrastructure_manager.node_tpu_processes(hostname).items():
                if proc_uid != uid:
                    continue
                if any(proc.get("user") and proc["user"] != owner for proc in procs):
                    return True
        return False
