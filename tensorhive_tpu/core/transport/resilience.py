"""Control-plane resilience: per-host circuit breakers + retry/backoff budgets.

The transport layer used to be fail-fast-and-forget: one ``TransportError``
in the fan-out produced a synthetic exit-255 result and the monitors came
back ~2 s later to hammer the same dead host with a fresh full-timeout SSH
attempt. Nothing distinguished a transient blip (retry it, cheaply) from a
down node (stop paying the timeout for it). JIRIAF-style provisioning
layers (PAPERS arxiv 2502.18596) model node health as an explicit state
machine for exactly this reason; this module gives every managed host one:

* :class:`CircuitBreaker` — classic closed → open → half-open per host.
  ``failure_threshold`` consecutive *channel* failures (TransportError, not
  non-zero exit codes — a host that answers with exit 1 is reachable) trip
  the breaker open for ``cooldown_s`` seconds (+ deterministic-given-rng
  jitter so a fleet of breakers does not re-probe in lockstep). After the
  cool-down the next caller is granted one of ``half_open_probes`` probe
  slots: success closes the breaker, failure re-opens it with a fresh
  cool-down.
* :class:`TransportResilience` — one registry of breakers per
  :class:`~.base.TransportManager`, plus the retry policy wrapped around
  every ``Transport.run``: bounded attempts (``1 + ssh.num_retries``),
  exponential backoff with **full jitter** (AWS-style:
  ``uniform(0, min(cap, base·2^attempt))``), and a per-call deadline budget
  so retries can never exceed the caller's timeout — an unreachable host
  costs at most the time the caller already agreed to wait, never a retry
  storm on top of it.

Clock, sleep, and rng are injectable so the whole state machine is testable
(and chaos-smokeable, tools/chaos_smoke.py) on a fake clock with zero real
waiting. Everything is thread-safe: breakers are shared by the fan-out pool
and single-host callers.

Exported metrics (docs/OBSERVABILITY.md, docs/ROBUSTNESS.md):

* ``tpuhive_transport_breaker_state{host}`` — 0 closed, 1 half-open, 2 open;
* ``tpuhive_transport_breaker_transitions_total{host,to}`` — one increment
  per state transition (the chaos smoke asserts exactly-once per phase);
* ``tpuhive_transport_retries_total{host,outcome}`` — calls that needed a
  retry, by how the retry loop ended (``success``, ``exhausted``,
  ``deadline``).
"""
from __future__ import annotations

import random
import threading
import time
from typing import TYPE_CHECKING, Callable, Dict, List, Optional

from ...observability import get_registry
from ...utils.exceptions import TransportError

if TYPE_CHECKING:
    from ...config import Config
    from .base import CommandResult

#: breaker states; the gauge encodes them in escalation order so
#: ``max(gauge)`` over hosts is "worst breaker in the fleet"
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

_BREAKER_STATE = get_registry().gauge(
    "tpuhive_transport_breaker_state",
    "Per-host circuit-breaker state: 0 closed, 1 half-open, 2 open.",
    labels=("host",))
_BREAKER_TRANSITIONS = get_registry().counter(
    "tpuhive_transport_breaker_transitions_total",
    "Circuit-breaker state transitions per host, by target state.",
    labels=("host", "to"))
_RETRIES_TOTAL = get_registry().counter(
    "tpuhive_transport_retries_total",
    "Transport calls that needed at least one retry, by how the retry "
    "loop ended (success, exhausted, deadline).",
    labels=("host", "outcome"))


class BreakerOpenError(TransportError):
    """Raised instead of attempting a round-trip while a host's breaker is
    open. Subclasses TransportError so every existing channel-failure path
    (monitor isolation, ``Transport.test``, nursery) handles it — just much
    faster than a timeout."""

    def __init__(self, hostname: str, retry_in_s: float,
                 consecutive_failures: int) -> None:
        self.hostname = hostname
        self.retry_in_s = retry_in_s
        super().__init__(
            f"[{hostname}] circuit open after {consecutive_failures} "
            f"consecutive failures; next probe in {retry_in_s:.1f}s")


class CircuitBreaker:
    """One host's failure state machine; thread-safe.

    Only *channel* failures count: callers record a failure when the
    transport raised (unreachable/auth/timeout), a success when a round-trip
    completed — whatever the remote exit code was.
    """

    def __init__(
        self,
        hostname: str,
        failure_threshold: int = 3,
        cooldown_s: float = 30.0,
        cooldown_jitter: float = 0.1,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.hostname = hostname
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self.cooldown_jitter = max(0.0, float(cooldown_jitter))
        self.half_open_probes = max(1, int(half_open_probes))
        self._clock = clock
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._open_until = 0.0
        self._probes_left = 0
        self._opened_count = 0
        _BREAKER_STATE.labels(host=hostname).set(STATE_VALUES[CLOSED])

    # -- introspection -------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive_failures

    @property
    def opened_count(self) -> int:
        with self._lock:
            return self._opened_count

    def retry_in_s(self) -> float:
        """Seconds until an open breaker grants a half-open probe (0 when
        not open)."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._open_until - self._clock())

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "opened_count": self._opened_count,
                "retry_in_s": (max(0.0, self._open_until - self._clock())
                               if self._state == OPEN else 0.0),
            }

    # -- state machine -------------------------------------------------------
    def allow(self) -> bool:
        """May a call proceed right now? Open breakers refuse until the
        cool-down elapses, then grant up to ``half_open_probes`` probes."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() < self._open_until:
                    return False
                self._transition(HALF_OPEN)
                self._probes_left = self.half_open_probes
            # HALF_OPEN: hand out the remaining probe budget; everyone else
            # waits for the probes' verdict instead of stampeding the host
            if self._probes_left > 0:
                self._probes_left -= 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> int:
        """Count one channel failure; returns the new consecutive streak."""
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._trip()                 # probe failed: fresh cool-down
            elif (self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._trip()
            return self._consecutive_failures

    def _trip(self) -> None:
        # jitter spreads re-probe times across the fleet: cooldown ..
        # cooldown*(1+jitter), deterministic given the injected rng
        jitter = 1.0 + self.cooldown_jitter * self._rng.random()
        self._open_until = self._clock() + self.cooldown_s * jitter
        self._opened_count += 1
        self._transition(OPEN)

    def _transition(self, to: str) -> None:
        # caller holds self._lock
        self._state = to
        _BREAKER_STATE.labels(host=self.hostname).set(STATE_VALUES[to])
        _BREAKER_TRANSITIONS.labels(host=self.hostname, to=to).inc()


class TransportResilience:
    """Per-manager breaker registry + the retry policy around every call.

    ``call(host, fn, timeout)`` is the single protected entry point: both
    the ``run_on_all`` fan-out and cached single-host transports route
    through it, so a host's failure streak is one number no matter which
    path observed the failures.
    """

    def __init__(
        self,
        config: Optional["Config"] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ) -> None:
        if config is None:
            from ...config import get_config

            config = get_config()
        ssh = config.ssh
        self.default_timeout_s = float(ssh.timeout_s)
        self.max_attempts = 1 + max(0, int(ssh.num_retries))
        self.backoff_base_s = float(ssh.retry_backoff_base_s)
        self.backoff_max_s = float(ssh.retry_backoff_max_s)
        self.failure_threshold = int(ssh.breaker_failure_threshold)
        self.cooldown_s = float(ssh.breaker_cooldown_s)
        self.cooldown_jitter = float(ssh.breaker_cooldown_jitter)
        self.half_open_probes = int(ssh.breaker_half_open_probes)
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    # -- breaker registry ----------------------------------------------------
    def breaker(self, hostname: str) -> CircuitBreaker:
        with self._lock:
            breaker = self._breakers.get(hostname)
            if breaker is None:
                breaker = CircuitBreaker(
                    hostname,
                    failure_threshold=self.failure_threshold,
                    cooldown_s=self.cooldown_s,
                    cooldown_jitter=self.cooldown_jitter,
                    half_open_probes=self.half_open_probes,
                    clock=self._clock,
                    rng=self._rng,
                )
                self._breakers[hostname] = breaker
            return breaker

    def open_hosts(self) -> List[str]:
        """Hosts whose breaker is currently refusing calls (open AND still
        inside the cool-down — a breaker one ``allow()`` away from granting
        a half-open probe is not 'skipped', it is about to be probed)."""
        with self._lock:
            breakers = list(self._breakers.items())
        return sorted(host for host, breaker in breakers
                      if breaker.state == OPEN)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            breakers = list(self._breakers.items())
        return {host: breaker.snapshot() for host, breaker in breakers}

    # -- the protected call path --------------------------------------------
    def call(self, hostname: str, fn: Callable[[Optional[float]], "CommandResult"],
             timeout: Optional[float] = None) -> "CommandResult":
        """Run ``fn(attempt_timeout)`` under breaker + retry protection.

        ``fn`` receives the per-attempt timeout; ``TransportError`` counts as
        a channel failure (retried while budget remains), anything it
        *returns* — including non-zero exits — is a success for the breaker.

        The deadline budget: with an explicit caller ``timeout``, the whole
        loop (attempts + backoff sleeps) fits inside it. With ``timeout=None``
        each attempt gets the configured default and the budget is
        ``default · max_attempts`` — still bounded, never unbounded waiting.
        """
        breaker = self.breaker(hostname)
        if not breaker.allow():
            raise BreakerOpenError(hostname, breaker.retry_in_s(),
                                   breaker.consecutive_failures)
        per_attempt = timeout if timeout is not None else self.default_timeout_s
        budget = (timeout if timeout is not None
                  else self.default_timeout_s * self.max_attempts)
        deadline = self._clock() + budget
        attempt = 0
        while True:
            attempt += 1
            remaining = deadline - self._clock()
            attempt_timeout = max(0.001, min(per_attempt, remaining))
            try:
                result = fn(attempt_timeout)
            except BreakerOpenError:
                raise
            except TransportError:
                breaker.record_failure()
                if attempt >= self.max_attempts:
                    if attempt > 1:
                        _RETRIES_TOTAL.labels(
                            host=hostname, outcome="exhausted").inc()
                    raise
                if breaker.state == OPEN:
                    # the streak just tripped the breaker: stop hammering,
                    # the cool-down owns the next contact with this host
                    raise
                delay = self._backoff(attempt)
                if self._clock() + delay >= deadline:
                    _RETRIES_TOTAL.labels(
                        host=hostname, outcome="deadline").inc()
                    raise
                self._sleep(delay)
                continue
            breaker.record_success()
            if attempt > 1:
                _RETRIES_TOTAL.labels(host=hostname, outcome="success").inc()
            return result

    def _backoff(self, attempt: int) -> float:
        """Full jitter: uniform over [0, min(cap, base·2^(attempt-1))] —
        decorrelates retry waves across hosts and callers."""
        cap = min(self.backoff_max_s, self.backoff_base_s * (2 ** (attempt - 1)))
        return self._rng.uniform(0.0, cap)
