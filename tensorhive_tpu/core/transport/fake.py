"""In-process fake cluster for tests.

The reference has no fake SSH backend — monitors, services, and the nursery
are untested against live-host behavior (SURVEY.md §4 "There is no fake SSH
backend and no multi-node simulation"). This module provides:

* :class:`FakeCluster` — in-memory hosts with processes, PTY sessions, task
  logs, and per-chip telemetry that tests mutate directly;
* :class:`FakeTransport` — a Transport whose ``run`` dispatches to canned
  command handlers (for code that fans raw commands out);
* :class:`FakeHostOps` — a HostOps implementation backed by the cluster
  (for the nursery / services seam).
"""
from __future__ import annotations

import dataclasses
import itertools
import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ...config import HostConfig
from ...utils.exceptions import SpawnError, TransportError
from ..nursery import HostOps, OpsFactory, Termination
from .base import CommandResult, Transport


@dataclass
class FaultPlan:
    """Deterministic, seeded fault injection for one fake host.

    Attached via :meth:`FakeCluster.set_fault_plan`; every
    :meth:`FakeTransport.run` consults the plan before (and after) executing
    the canned handlers, so monitors, the nursery, and job scheduling can be
    chaos-tested in-process without sleeping or flaking:

    * ``fail_next`` — the next N calls raise ``TransportError`` outright;
    * ``flap_every`` — every K-th call fails (counted per plan, so a plan
      with ``flap_every=3`` fails calls 3, 6, 9, …);
    * ``fail_probability`` — seeded coin per call: deterministic given
      ``seed`` and the call order;
    * ``latency_s`` — injected round-trip latency; when the call carries a
      timeout smaller than the latency it raises a timeout-shaped
      ``TransportError`` (no real sleeping — the latency is *modeled*, which
      keeps chaos runs instant and exact);
    * ``partial_stdout_chars`` — truncate successful stdout (a cut
      connection mid-reply: drives the probe's unparseable-output path).

    Membership-churn events (docs/ROBUSTNESS.md "Host membership &
    leases") make agent/preemption chaos deterministic too:

    * ``preempt_at`` — on the Nth transport call the host is preempted
      (:meth:`FakeCluster.preempt_host`: processes killed, host
      unreachable), exactly once — the mid-job revocation a preemptible
      TPU VM delivers;
    * ``agent_silence`` — the next N agent heartbeats are dropped before
      sending (agent death / network partition as seen by the lease plane);
    * ``duplicate_reports`` — the next N agent heartbeats are sent twice
      (at-least-once delivery; the server's seq idempotence must absorb it);
    * ``clock_skew_s`` — skews the agent's self-reported ``sent_ts``; the
      server leases on ITS OWN clock, so tests pin that skew is harmless.

    Every injected failure increments :attr:`faults_injected`;
    :attr:`calls` counts all calls that consulted the plan (the chaos smoke
    asserts an open breaker stops the counter moving).
    """

    seed: int = 0
    fail_next: int = 0
    flap_every: int = 0
    fail_probability: float = 0.0
    latency_s: float = 0.0
    partial_stdout_chars: Optional[int] = None
    preempt_at: int = 0
    agent_silence: int = 0
    duplicate_reports: int = 0
    clock_skew_s: float = 0.0
    error: str = "injected fault"

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        with self._lock:
            self.calls = 0
            self.faults_injected = 0
            self._preempted = False

    def before_call(self, hostname: str, command: str,
                    timeout: Optional[float]) -> None:
        """Raise the planned ``TransportError`` for this call, if any."""
        with self._lock:
            self.calls += 1
            reason = None
            if self.fail_next > 0:
                self.fail_next -= 1
                reason = "fail_next"
            elif self.flap_every and self.calls % self.flap_every == 0:
                reason = "flap"
            elif (self.fail_probability
                    and self._rng.random() < self.fail_probability):
                reason = "seeded"
            elif (self.latency_s and timeout is not None
                    and self.latency_s > timeout):
                reason = f"latency {self.latency_s:g}s > timeout {timeout:g}s"
            if reason is not None:
                self.faults_injected += 1
                raise TransportError(
                    f"[{hostname}] {self.error} ({reason})")

    def after_result(self, result: CommandResult) -> CommandResult:
        with self._lock:
            if self.partial_stdout_chars is not None:
                return dataclasses.replace(
                    result, stdout=result.stdout[:self.partial_stdout_chars])
            return result

    def take_preemption(self) -> bool:
        """True exactly once, when the call counter has reached
        ``preempt_at`` — the transport layer then preempts the host."""
        with self._lock:
            if (self.preempt_at and not self._preempted
                    and self.calls >= self.preempt_at):
                self._preempted = True
                self.faults_injected += 1
                return True
            return False

    def agent_event(self) -> str:
        """Consumed by :class:`~...core.agent.HostAgent` once per heartbeat:
        ``silence`` (drop the report), ``duplicate`` (send it twice) or
        ``send`` (normal delivery)."""
        with self._lock:
            if self.agent_silence > 0:
                self.agent_silence -= 1
                self.faults_injected += 1
                return "silence"
            if self.duplicate_reports > 0:
                self.duplicate_reports -= 1
                self.faults_injected += 1
                return "duplicate"
            return "send"


@dataclass
class FakeProcess:
    pid: int
    user: str
    command: str
    task_id: Optional[int] = None
    chip_ids: List[int] = field(default_factory=list)
    alive: bool = True
    received_signals: List[str] = field(default_factory=list)
    # how many signals of each kind it takes before the process dies
    dies_on: Tuple[str, ...] = ("INT", "TERM", "KILL")


@dataclass
class FakeHost:
    name: str
    processes: Dict[int, FakeProcess] = field(default_factory=dict)
    ptys: List[Tuple[str, str]] = field(default_factory=list)  # (user, tty)
    pty_messages: Dict[str, List[str]] = field(default_factory=dict)
    task_logs: Dict[int, str] = field(default_factory=dict)
    reachable: bool = True
    # chip telemetry: chip_index -> metrics dict (mutated by tests)
    chips: Dict[int, Dict] = field(default_factory=dict)
    # cumulative cpu jiffies + memory, advanced by tests for util deltas
    cpu_total_jiffies: int = 0
    cpu_idle_jiffies: int = 0
    ncpu: int = 8
    mem_total_kb: int = 16 * 2**20
    mem_avail_kb: int = 12 * 2**20
    # what the probe reports for per-chip kernel counters: "ok" (healthy
    # default) or "absent" (tests flip it to exercise the blind-telemetry
    # warning path)
    sysfs_status: str = "ok"


class FakeCluster:
    def __init__(self) -> None:
        self.hosts: Dict[str, FakeHost] = {}
        self._pid_counter = itertools.count(1000)
        self._lock = threading.RLock()
        self.spawn_failures: Dict[str, str] = {}  # hostname -> error message
        #: per-host deterministic fault injection (chaos harness)
        self.fault_plans: Dict[str, FaultPlan] = {}

    def set_fault_plan(self, hostname: str, plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
        """Attach (or with None: clear) a host's fault plan; returns it."""
        if plan is None:
            self.fault_plans.pop(hostname, None)
        else:
            self.fault_plans[hostname] = plan
        return plan

    def add_host(self, name: str, chips: int = 0, accel: str = "v5litepod-8") -> FakeHost:
        host = FakeHost(name=name)
        for index in range(chips):
            host.chips[index] = {
                "uid": f"{name}:tpu:{index}",
                "index": index,
                "accelerator_type": accel,
                "hbm_used_bytes": 0,
                "hbm_total_bytes": 16 * 2**30,
                "duty_cycle_pct": 0.0,
                "pid": None,
                "user": None,
            }
        self.hosts[name] = host
        return host

    def host(self, name: str) -> FakeHost:
        try:
            return self.hosts[name]
        except KeyError:
            raise TransportError(f"fake cluster has no host {name!r}")

    def next_pid(self) -> int:
        return next(self._pid_counter)

    def start_process(
        self,
        hostname: str,
        user: str,
        command: str = "python burn.py",
        chip_ids: Optional[List[int]] = None,
        **kwargs,
    ) -> FakeProcess:
        """Simulate a user process occupying chips (for protection tests)."""
        with self._lock:
            host = self.host(hostname)
            proc = FakeProcess(
                pid=self.next_pid(), user=user, command=command,
                chip_ids=chip_ids or [], **kwargs,
            )
            host.processes[proc.pid] = proc
            for chip in proc.chip_ids:
                if chip in host.chips:
                    host.chips[chip]["pid"] = proc.pid
                    host.chips[chip]["user"] = user
            return proc

    def preempt_host(self, hostname: str) -> None:
        """Preemptible-capacity revocation: every process dies and the host
        drops off the network in one step (the cloud reclaiming a VM)."""
        with self._lock:
            host = self.host(hostname)
            for pid, proc in host.processes.items():
                proc.alive = False
                for chip in proc.chip_ids:
                    if chip in host.chips and host.chips[chip].get("pid") == pid:
                        host.chips[chip]["pid"] = None
                        host.chips[chip]["user"] = None
            host.reachable = False

    def restore_host(self, hostname: str) -> None:
        """Bring a preempted host back (re-provisioned VM re-joining)."""
        with self._lock:
            self.host(hostname).reachable = True

    def kill_process(self, hostname: str, pid: int) -> None:
        with self._lock:
            host = self.host(hostname)
            proc = host.processes.get(pid)
            if proc is not None:
                proc.alive = False
                for chip in proc.chip_ids:
                    if chip in host.chips and host.chips[chip].get("pid") == pid:
                        host.chips[chip]["pid"] = None
                        host.chips[chip]["user"] = None

    def probe_json(self, hostname: str) -> str:
        """Render this host's state in the probe's schema-v1 JSON, so fake
        monitoring traverses the exact same parse path as production."""
        from ..monitors.probe import render_probe_json

        with self._lock:
            host = self.host(hostname)
            chips, metrics = [], {}
            for index, chip in sorted(host.chips.items()):
                pids = sorted({
                    pid for pid, proc in host.processes.items()
                    if proc.alive and index in proc.chip_ids
                } | ({chip["pid"]} if chip.get("pid") else set()))
                chips.append({"index": index, "dev": f"/dev/accel{index}", "pids": pids})
                metrics[str(index)] = {
                    "hbm_used_bytes": chip.get("hbm_used_bytes"),
                    "hbm_total_bytes": chip.get("hbm_total_bytes"),
                    "duty_cycle_pct": chip.get("duty_cycle_pct"),
                    "age_s": chip.get("metrics_age_s", 0.0),
                }
            procs = {
                pid: {"user": proc.user, "cmd": proc.command}
                for pid, proc in host.processes.items()
                if proc.alive
            }
            return render_probe_json(
                chips, procs,
                cpu={"total": host.cpu_total_jiffies, "idle": host.cpu_idle_jiffies,
                     "ncpu": host.ncpu},
                mem={"total_kb": host.mem_total_kb, "avail_kb": host.mem_avail_kb},
                metrics=metrics,
                sysfs_status=host.sysfs_status,
            )


class FakeTransport(Transport):
    """Transport running canned handlers instead of a shell. Tests register
    handlers via ``cluster.command_handlers`` or per-instance ``on()``."""

    def __init__(self, host: HostConfig, cluster: FakeCluster, user: Optional[str] = None, config=None) -> None:
        super().__init__(host, user)
        self.cluster = cluster
        self._handlers: List[Tuple[Callable[[str], bool], Callable[[str], str]]] = []

    def on(self, predicate: Callable[[str], bool], respond: Callable[[str], str]) -> None:
        self._handlers.append((predicate, respond))

    def run(self, command: str, timeout: Optional[float] = None,
            idempotent: bool = True) -> CommandResult:
        fake_host = self.cluster.host(self.hostname)
        plan = self.cluster.fault_plans.get(self.hostname)
        if plan is not None:
            plan.before_call(self.hostname, command, timeout)
            if plan.take_preemption():
                self.cluster.preempt_host(self.hostname)
        if not fake_host.reachable:
            raise TransportError(f"[{self.hostname}] unreachable (fake)")
        result = self._dispatch(command)
        if plan is not None:
            result = plan.after_result(result)
        return result

    def _dispatch(self, command: str) -> CommandResult:
        for predicate, respond in self._handlers:
            if predicate(command):
                return CommandResult(self.hostname, command, 0, respond(command))
        if command.strip() == "uname":
            return CommandResult(self.hostname, command, 0, "Linux\n")
        from ..monitors.probe import PROBE_MARKER

        if PROBE_MARKER in command:
            return CommandResult(
                self.hostname, command, 0, self.cluster.probe_json(self.hostname) + "\n"
            )
        return CommandResult(self.hostname, command, 127, "", f"fake: unhandled command {command!r}")


class FakeHostOps(HostOps):
    """HostOps semantics against the in-memory cluster (no shell)."""

    def __init__(self, cluster: FakeCluster, hostname: str, user: Optional[str] = None) -> None:
        self.cluster = cluster
        self._hostname = hostname
        self.user = user
        self.transport = None  # type: ignore[assignment]

    @property
    def hostname(self) -> str:
        return self._hostname

    def _host(self) -> FakeHost:
        host = self.cluster.host(self._hostname)
        if not host.reachable:
            raise TransportError(f"[{self._hostname}] unreachable (fake)")
        return host

    # -- task lifecycle ----------------------------------------------------
    def spawn(self, command: str, task_id: int, timeout: Optional[float] = None) -> int:
        host = self._host()
        if self._hostname in self.cluster.spawn_failures:
            raise SpawnError(self.cluster.spawn_failures[self._hostname])
        proc = FakeProcess(
            pid=self.cluster.next_pid(),
            user=self.user or "tpuhive",
            command=command,
            task_id=task_id,
        )
        host.processes[proc.pid] = proc
        host.task_logs[task_id] = f"[fake] started: {command}\n"
        return proc.pid

    def terminate(self, pid: int, mode: Termination = Termination.interrupt) -> bool:
        mode = Termination(mode)
        host = self._host()
        proc = host.processes.get(pid)
        if proc is None or not proc.alive:
            return False
        proc.received_signals.append(mode.value)
        if mode.value in proc.dies_on:
            proc.alive = False
            if proc.task_id is not None and proc.task_id in host.task_logs:
                host.task_logs[proc.task_id] += f"[fake] terminated by SIG{mode.value}\n"
        return True

    def running_tasks(self) -> Dict[int, int]:
        host = self._host()
        return {
            proc.task_id: pid
            for pid, proc in host.processes.items()
            if proc.alive and proc.task_id is not None
        }

    def fetch_log(self, task_id: int, tail: Optional[int] = None) -> str:
        host = self._host()
        if task_id not in host.task_logs:
            raise TransportError(f"[{self._hostname}] no log for task {task_id}")
        text = host.task_logs[task_id]
        if tail:
            return "\n".join(text.splitlines()[-tail:]) + "\n"
        return text

    def remove_log(self, task_id: int) -> None:
        self._host().task_logs.pop(task_id, None)

    # -- generic process ops -----------------------------------------------
    def kill_pid(self, pid: int, sig: int = 9, sudo: bool = False) -> bool:
        host = self._host()
        proc = host.processes.get(pid)
        if proc is None or not proc.alive:
            return False
        if not sudo and self.user is not None and proc.user != self.user:
            return False  # no permission, mirrors kill(1) EPERM
        proc.received_signals.append(str(sig))
        if sig in (9, 15):
            self.cluster.kill_process(self._hostname, pid)
        return True

    def process_owner(self, pid: int) -> Optional[str]:
        proc = self._host().processes.get(pid)
        return proc.user if proc is not None and proc.alive else None

    def process_owners(self, pids: List[int]) -> Dict[int, str]:
        return {p: owner for p in pids if (owner := self.process_owner(p)) is not None}

    # -- PTY ops -----------------------------------------------------------
    def pty_sessions(self) -> List[Tuple[str, str]]:
        return list(self._host().ptys)

    def write_to_ptys(self, ttys: List[str], message: str) -> None:
        host = self._host()
        for tty in ttys:
            host.pty_messages.setdefault(tty, []).append(message)


class FakeOpsFactory(OpsFactory):
    def __init__(self, cluster: FakeCluster) -> None:
        super().__init__(transport_manager=None)
        self.cluster = cluster

    def ops_for(self, hostname: str, user: Optional[str] = None) -> FakeHostOps:
        return FakeHostOps(self.cluster, hostname, user=user)

    @property
    def hostnames(self) -> List[str]:
        return list(self.cluster.hosts)
