"""OpenSSH-client transport (control plane to TPU VMs).

Reference: tensorhive/core/ssh.py wraps parallel-ssh (libssh2 C bindings,
setup.py:57,71). This rebuild shells out to the system ``ssh`` binary in
BatchMode with connection multiplexing (ControlMaster), which gives
libssh2-class amortized latency (one TCP/auth handshake per host, reused by
every subsequent command) with zero Python-level dependencies. Proxy-jump
support mirrors the reference's ``[proxy_tunneling]`` section
(config.py:137-150).
"""
from __future__ import annotations

import shlex
import shutil
import subprocess
from typing import List, Optional

from ...config import Config, HostConfig
from ...utils.exceptions import TransportError
from .base import CommandResult, Transport, register_backend


class SshTransport(Transport):
    def __init__(self, host: HostConfig, user: Optional[str] = None, config: Optional[Config] = None) -> None:
        super().__init__(host, user)
        if shutil.which("ssh") is None:
            raise TransportError(
                "openssh client not found on PATH; use backend='local' or 'fake'"
            )
        self._config = config
        self.timeout_s = config.ssh.timeout_s if config else 10.0

    def _common_options(self) -> List[str]:
        """Options shared by ssh and scp invocations (port excluded: ssh
        spells it -p, scp spells it -P)."""
        cfg = self._config
        args = [
            "-o", "BatchMode=yes",
            "-o", "StrictHostKeyChecking=accept-new",
            "-o", f"ConnectTimeout={int(self.timeout_s)}",
            # multiplex: reuse one authenticated connection per (host,user)
            "-o", "ControlMaster=auto",
            "-o", "ControlPersist=60s",
            "-o", "ControlPath=~/.ssh/tpuhive-%r@%h:%p",
        ]
        if cfg is not None:
            key_path = cfg.ssh_key_path
            if key_path.exists():
                args += ["-i", str(key_path)]
            if cfg.ssh.proxy_host:
                proxy_user = cfg.ssh.proxy_user or self.user
                args += [
                    "-J", f"{proxy_user}@{cfg.ssh.proxy_host}:{cfg.ssh.proxy_port}"
                ]
        return args

    def _base_args(self) -> List[str]:
        return ["ssh"] + self._common_options() + ["-p", str(self.host.port)]

    def run(self, command: str, timeout: Optional[float] = None,
            idempotent: bool = True) -> CommandResult:
        target = f"{self.user}@{self.host.address}" if self.user else self.host.address
        argv = self._base_args() + [target, command]
        try:
            proc = subprocess.run(
                argv,
                capture_output=True,
                text=True,
                timeout=(timeout or self.timeout_s) + self.timeout_s,
            )
        except subprocess.TimeoutExpired as exc:
            raise TransportError(
                f"[{self.hostname}] ssh timed out running {command!r}"
            ) from exc
        except OSError as exc:
            raise TransportError(f"[{self.hostname}] ssh exec failed: {exc}") from exc
        if proc.returncode == 255 and _looks_like_ssh_failure(proc.stderr):
            # 255 is ssh's own "connection/auth failed" exit code, but a
            # remote command may legitimately exit 255 too — only treat it as
            # a channel failure when stderr carries ssh's own diagnostics
            raise TransportError(
                f"[{self.hostname}] ssh connection failed: {proc.stderr.strip()}"
            )
        return CommandResult(
            host=self.hostname,
            command=command,
            exit_code=proc.returncode,
            stdout=proc.stdout,
            stderr=proc.stderr,
        )


    def put_file(self, local_path: str, remote_path: str, mode: int = 0o755) -> None:
        """scp with the same multiplexed connection options as run()."""
        target = f"{self.user}@{self.host.address}" if self.user else self.host.address
        remote_path = self.expand_remote_path(remote_path)
        self.check_output(f'mkdir -p "$(dirname {shlex.quote(remote_path)})"')
        argv = ["scp"] + self._common_options() + ["-P", str(self.host.port),
                local_path, f"{target}:{remote_path}"]
        try:
            proc = subprocess.run(argv, capture_output=True, text=True,
                                  timeout=self.timeout_s * 6)
        except (subprocess.TimeoutExpired, OSError) as exc:
            raise TransportError(f"[{self.hostname}] scp failed: {exc}") from exc
        if proc.returncode != 0:
            raise TransportError(
                f"[{self.hostname}] scp failed: {proc.stderr.strip()}"
            )
        self.check_output(f"chmod {mode:o} {shlex.quote(remote_path)}")


_SSH_FAILURE_MARKERS = (
    "ssh:",                    # "ssh: connect to host ... "
    "Permission denied",
    "Host key verification failed",
    "Connection timed out",
    "Connection refused",
    "Connection closed",
    "kex_exchange",
    "Could not resolve hostname",
    "No route to host",
)


def _looks_like_ssh_failure(stderr: str) -> bool:
    return any(marker in stderr for marker in _SSH_FAILURE_MARKERS)


def generate_keypair(key_path) -> str:
    """Create the manager's RSA keypair if absent; return the public key
    (reference: core/ssh.py:131-146 generate_cert/init_ssh_key)."""
    import os

    key_path = str(key_path)
    if not os.path.exists(key_path):
        os.makedirs(os.path.dirname(key_path), exist_ok=True)
        if shutil.which("ssh-keygen") is None:
            raise TransportError("ssh-keygen not available to create key")
        subprocess.run(
            ["ssh-keygen", "-t", "rsa", "-b", "3072", "-N", "", "-f", key_path, "-q"],
            check=True,
        )
        os.chmod(key_path, 0o600)
    with open(key_path + ".pub") as fh:
        return fh.read().strip()


register_backend("ssh", SshTransport)
