"""Remote-execution transports (reference: tensorhive/core/ssh.py +
core/managers/SSHConnectionManager.py).

The reference hardwires parallel-ssh/libssh2; this rebuild defines a narrow
:class:`Transport` interface with three interchangeable backends:

* ``ssh``   — OpenSSH client subprocess fan-out (control plane to TPU VMs),
* ``local`` — subprocess on this machine (single-VM installs, localhost jobs),
* ``fake``  — in-process simulated cluster, closing the reference's test gap
  (SURVEY.md §4: "There is no fake SSH backend and no multi-node simulation").
"""
from .base import CommandResult, ResilientTransport, Transport, TransportManager, get_transport_manager, set_transport_manager  # noqa: F401
from .resilience import BreakerOpenError, CircuitBreaker, TransportResilience  # noqa: F401
from .local import LocalTransport  # noqa: F401
from .ssh import SshTransport  # noqa: F401
from .fake import FakeCluster, FakeTransport, FaultPlan  # noqa: F401
