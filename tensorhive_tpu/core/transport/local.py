"""Local subprocess transport.

Runs commands directly on this machine — the degenerate "cluster of one"
case: single-VM installs where the manager daemon runs on the TPU VM itself,
and the localhost CPU example (BASELINE.json config 1). Also the transport
that makes the nursery's real shell path exercisable in CI without SSH.
"""
from __future__ import annotations

import subprocess
from typing import Optional

from ...config import Config, HostConfig
from ...utils.exceptions import TransportError
from .base import CommandResult, Transport, register_backend


class LocalTransport(Transport):
    def __init__(self, host: HostConfig, user: Optional[str] = None, config: Optional[Config] = None) -> None:
        super().__init__(host, user)
        self.timeout_s = (config.ssh.timeout_s if config else 10.0)

    def run(self, command: str, timeout: Optional[float] = None,
            idempotent: bool = True) -> CommandResult:
        try:
            proc = subprocess.run(
                ["bash", "-c", command],
                capture_output=True,
                text=True,
                timeout=timeout or self.timeout_s,
            )
        except subprocess.TimeoutExpired as exc:
            raise TransportError(f"[{self.hostname}] local command timed out: {command!r}") from exc
        except OSError as exc:
            raise TransportError(f"[{self.hostname}] local exec failed: {exc}") from exc
        return CommandResult(
            host=self.hostname,
            command=command,
            exit_code=proc.returncode,
            stdout=proc.stdout,
            stderr=proc.stderr,
        )


    def put_file(self, local_path: str, remote_path: str, mode: int = 0o755) -> None:
        import os
        import shutil

        expanded = os.path.expandvars(os.path.expanduser(remote_path))
        os.makedirs(os.path.dirname(expanded) or ".", exist_ok=True)
        shutil.copyfile(local_path, expanded)
        os.chmod(expanded, mode)


register_backend("local", LocalTransport)
