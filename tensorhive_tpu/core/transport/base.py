"""Transport interface + manager (reference: core/ssh.py:53-128 stateless API,
core/managers/SSHConnectionManager.py:11-121 stateful cache + group fan-out).
"""
from __future__ import annotations

import concurrent.futures
import dataclasses
import logging
import threading
import time
from shlex import quote as shlex_quote
from typing import Callable, Dict, List, Optional, Tuple

from ...config import Config, HostConfig, get_config
from ...observability import get_registry, get_tracer
from ...utils.exceptions import TransportError

log = logging.getLogger(__name__)

# per-host command round-trips: the monitoring fan-out drives one of these
# per host per ~2 s tick, so this histogram IS the cluster's SSH-latency view
_COMMAND_SECONDS = get_registry().histogram(
    "tpuhive_transport_command_seconds",
    "Remote command round-trip latency per host (fan-out path).",
    labels=("host",))
_COMMANDS_TOTAL = get_registry().counter(
    "tpuhive_transport_commands_total",
    "Remote commands by host and outcome (ok, error, unreachable).",
    labels=("host", "outcome"))


@dataclasses.dataclass
class CommandResult:
    host: str
    command: str
    exit_code: int
    stdout: str
    stderr: str = ""

    @property
    def ok(self) -> bool:
        return self.exit_code == 0

    def stdout_lines(self) -> List[str]:
        return [line for line in self.stdout.splitlines() if line.strip()]


class Transport:
    """One (host, user) command channel."""

    def __init__(self, host: HostConfig, user: Optional[str] = None) -> None:
        self.host = host
        self.user = user or host.user

    @property
    def hostname(self) -> str:
        return self.host.name

    def run(self, command: str, timeout: Optional[float] = None) -> CommandResult:
        """Execute a shell command; returns CommandResult (non-zero exit codes
        are returned, not raised). Raises TransportError only when the channel
        itself fails (unreachable host, auth failure, timeout)."""
        raise NotImplementedError

    def check_output(self, command: str, timeout: Optional[float] = None) -> str:
        """run + raise TransportError on non-zero exit (reference
        ssh.get_stdout unwrap, core/ssh.py:98)."""
        result = self.run(command, timeout=timeout)
        if not result.ok:
            raise TransportError(
                f"[{self.hostname}] command failed (exit {result.exit_code}): "
                f"{command!r}: {result.stderr.strip() or result.stdout.strip()}"
            )
        return result.stdout

    def test(self) -> bool:
        """Connectivity probe (reference runs `uname` on every node,
        SSHConnectionManager.test_all_connections:76-121)."""
        try:
            return self.run("uname", timeout=10).ok
        except TransportError:
            return False

    def expand_remote_path(self, remote_path: str) -> str:
        """Resolve ``$HOME``/``~`` in a remote path against the host's actual
        home directory, so later uses can be safely shell-quoted (quoting a
        path that still contains ``$HOME`` would create a literal '$HOME'
        directory)."""
        if "$HOME" in remote_path or remote_path.startswith("~"):
            home = self.check_output('printf %s "$HOME"').strip()
            if not home:
                raise TransportError(f"[{self.hostname}] cannot resolve $HOME")
            remote_path = remote_path.replace("$HOME", home)
            if remote_path.startswith("~"):
                remote_path = home + remote_path[1:]
        return remote_path

    def put_file(self, local_path: str, remote_path: str, mode: int = 0o755) -> None:
        """Copy a local file onto the host. Default implementation streams
        base64 chunks through ``run`` (works over any command channel);
        backends with a real copy path (scp, cp) override it."""
        import base64

        with open(local_path, "rb") as fh:
            data = fh.read()
        encoded = base64.b64encode(data).decode()
        quoted = shlex_quote(self.expand_remote_path(remote_path))
        self.check_output(f'mkdir -p "$(dirname {quoted})" && : > {quoted}.b64')
        chunk_size = 64 * 1024  # keep each command line well under ARG_MAX
        try:
            for offset in range(0, len(encoded), chunk_size):
                chunk = encoded[offset:offset + chunk_size]
                self.check_output(f"printf %s {chunk} >> {quoted}.b64")
            self.check_output(
                f"base64 -d {quoted}.b64 > {quoted} && chmod {mode:o} {quoted}"
            )
        finally:
            self.run(f"rm -f {quoted}.b64")


_BACKENDS: Dict[str, Callable[..., Transport]] = {}


def register_backend(name: str, factory: Callable[..., Transport]) -> None:
    _BACKENDS[name] = factory


def make_transport(host: HostConfig, user: Optional[str] = None, config: Optional[Config] = None) -> Transport:
    config = config or get_config()
    backend = host.backend or config.ssh.default_backend
    try:
        factory = _BACKENDS[backend]
    except KeyError:
        raise TransportError(
            f"unknown transport backend {backend!r} for host {host.name} "
            f"(registered: {sorted(_BACKENDS)})"
        )
    return factory(host, user=user, config=config)


class TransportManager:
    """Caches per-(host, user) transports and fans commands out to many hosts
    in parallel (reference: SSHConnectionManager group client :21-46 +
    memoized per-user clients ssh.py:52-69; parallelism was gevent, here a
    thread pool with ``stop_on_errors=False`` semantics — per-host failures
    are isolated into the result map)."""

    def __init__(self, config: Optional[Config] = None, max_workers: int = 32) -> None:
        self.config = config or get_config()
        self._cache: Dict[Tuple[str, Optional[str]], Transport] = {}
        self._cache_lock = threading.Lock()
        # persistent pool: run_on_all fires once per monitor per ~2s tick, so
        # per-call executor construction would churn threads on the hot path
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="transport"
        )

    @property
    def hostnames(self) -> List[str]:
        return list(self.config.hosts)

    def add_host(self, host: HostConfig) -> None:
        """Dynamic host registration (reference SSHConnectionManager.add_host)."""
        self.config.hosts[host.name] = host

    def for_host(self, hostname: str, user: Optional[str] = None) -> Transport:
        key = (hostname, user)
        with self._cache_lock:
            if key not in self._cache:
                try:
                    host = self.config.hosts[hostname]
                except KeyError:
                    raise TransportError(f"unknown host {hostname!r}")
                self._cache[key] = make_transport(host, user=user, config=self.config)
            return self._cache[key]

    def invalidate(self, hostname: Optional[str] = None) -> None:
        with self._cache_lock:
            if hostname is None:
                self._cache.clear()
            else:
                for key in [k for k in self._cache if k[0] == hostname]:
                    del self._cache[key]

    def run_on_all(
        self,
        command: str,
        hostnames: Optional[List[str]] = None,
        timeout: Optional[float] = None,
    ) -> Dict[str, CommandResult]:
        """Parallel fan-out; failed hosts map to a synthetic non-zero result
        instead of raising (reference stop_on_errors=False, GPUMonitor.py:77)."""
        hostnames = hostnames if hostnames is not None else self.hostnames
        results: Dict[str, CommandResult] = {}
        if not hostnames:
            return results

        def _one(name: str) -> CommandResult:
            started = time.perf_counter()
            try:
                result = self.for_host(name).run(command, timeout=timeout)
                outcome = "ok" if result.ok else "error"
            except TransportError as exc:
                log.warning("host %s unreachable: %s", name, exc)
                outcome = "unreachable"
                result = CommandResult(
                    host=name, command=command, exit_code=255, stdout="", stderr=str(exc)
                )
            _COMMAND_SECONDS.labels(host=name).observe(
                time.perf_counter() - started)
            _COMMANDS_TOTAL.labels(host=name, outcome=outcome).inc()
            return result

        with get_tracer().span("transport.run_on_all", kind="transport",
                               hosts=len(hostnames)) as span:
            for name, result in zip(hostnames, self._pool.map(_one, hostnames)):
                results[name] = result
            failed = sum(1 for result in results.values() if not result.ok)
            span.attrs["failed"] = str(failed)
            if failed:
                span.status = "error"
        return results

    def close(self) -> None:
        self._pool.shutdown(wait=False)

    def test_all_connections(self) -> Dict[str, bool]:
        """Startup connectivity probe (reference TensorHiveManager.test_ssh:47-69)."""
        statuses = {}
        for name, result in self.run_on_all("uname").items():
            statuses[name] = result.ok
            if not result.ok:
                log.error("connectivity test failed for %s: %s", name, result.stderr)
        return statuses


# ---------------------------------------------------------------------------
_manager: Optional[TransportManager] = None
_manager_lock = threading.Lock()


def get_transport_manager() -> TransportManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = TransportManager()
        return _manager


def set_transport_manager(manager: Optional[TransportManager]) -> None:
    global _manager
    with _manager_lock:
        _manager = manager
