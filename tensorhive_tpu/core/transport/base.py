"""Transport interface + manager (reference: core/ssh.py:53-128 stateless API,
core/managers/SSHConnectionManager.py:11-121 stateful cache + group fan-out).
"""
from __future__ import annotations

import concurrent.futures
import dataclasses
import logging
import threading
import time
from shlex import quote as shlex_quote
from typing import Callable, Dict, List, Optional, Tuple

from ...config import Config, HostConfig, get_config
from ...observability import get_registry, get_tracer
from ...utils.exceptions import TransportError
from .resilience import BreakerOpenError, TransportResilience

log = logging.getLogger(__name__)

# per-host command round-trips: the monitoring fan-out drives one of these
# per host per ~2 s tick, so this histogram IS the cluster's SSH-latency view
_COMMAND_SECONDS = get_registry().histogram(
    "tpuhive_transport_command_seconds",
    "Remote command round-trip latency per host (fan-out path).",
    labels=("host",))
_COMMANDS_TOTAL = get_registry().counter(
    "tpuhive_transport_commands_total",
    "Remote commands by host and outcome (ok, error, unreachable, "
    "circuit_open).",
    labels=("host", "outcome"))


@dataclasses.dataclass
class CommandResult:
    host: str
    command: str
    exit_code: int
    stdout: str
    stderr: str = ""

    @property
    def ok(self) -> bool:
        return self.exit_code == 0

    def stdout_lines(self) -> List[str]:
        return [line for line in self.stdout.splitlines() if line.strip()]


class Transport:
    """One (host, user) command channel."""

    #: per-attempt deadline used when callers pass no timeout; backends
    #: built with a config overwrite this from ``config.ssh.timeout_s``
    timeout_s: float = 10.0

    def __init__(self, host: HostConfig, user: Optional[str] = None) -> None:
        self.host = host
        self.user = user or host.user

    @property
    def hostname(self) -> str:
        return self.host.name

    def run(self, command: str, timeout: Optional[float] = None,
            idempotent: bool = True) -> CommandResult:
        """Execute a shell command; returns CommandResult (non-zero exit codes
        are returned, not raised). Raises TransportError only when the channel
        itself fails (unreachable host, auth failure, timeout).

        ``idempotent=False`` marks commands with side effects that must not
        be re-issued on an ambiguous failure (a spawn that timed out may
        still have started its process); the resilient wrapper honors it by
        never retrying such calls — concrete backends ignore it.
        """
        raise NotImplementedError

    def check_output(self, command: str, timeout: Optional[float] = None) -> str:
        """run + raise TransportError on non-zero exit (reference
        ssh.get_stdout unwrap, core/ssh.py:98)."""
        result = self.run(command, timeout=timeout)
        if not result.ok:
            raise TransportError(
                f"[{self.hostname}] command failed (exit {result.exit_code}): "
                f"{command!r}: {result.stderr.strip() or result.stdout.strip()}"
            )
        return result.stdout

    def test(self) -> bool:
        """Connectivity probe (reference runs `uname` on every node,
        SSHConnectionManager.test_all_connections:76-121). Uses the
        configured per-attempt timeout, not a hardcoded one."""
        try:
            return self.run("uname", timeout=self.timeout_s).ok
        except TransportError:
            return False

    def expand_remote_path(self, remote_path: str) -> str:
        """Resolve ``$HOME``/``~`` in a remote path against the host's actual
        home directory, so later uses can be safely shell-quoted (quoting a
        path that still contains ``$HOME`` would create a literal '$HOME'
        directory)."""
        if "$HOME" in remote_path or remote_path.startswith("~"):
            home = self.check_output('printf %s "$HOME"').strip()
            if not home:
                raise TransportError(f"[{self.hostname}] cannot resolve $HOME")
            remote_path = remote_path.replace("$HOME", home)
            if remote_path.startswith("~"):
                remote_path = home + remote_path[1:]
        return remote_path

    def put_file(self, local_path: str, remote_path: str, mode: int = 0o755) -> None:
        """Copy a local file onto the host. Default implementation streams
        base64 chunks through ``run`` (works over any command channel);
        backends with a real copy path (scp, cp) override it."""
        import base64

        with open(local_path, "rb") as fh:
            data = fh.read()
        encoded = base64.b64encode(data).decode()
        quoted = shlex_quote(self.expand_remote_path(remote_path))
        self.check_output(f'mkdir -p "$(dirname {quoted})" && : > {quoted}.b64')
        chunk_size = 64 * 1024  # keep each command line well under ARG_MAX
        try:
            for offset in range(0, len(encoded), chunk_size):
                chunk = encoded[offset:offset + chunk_size]
                self.check_output(f"printf %s {chunk} >> {quoted}.b64")
            self.check_output(
                f"base64 -d {quoted}.b64 > {quoted} && chmod {mode:o} {quoted}"
            )
        finally:
            self.run(f"rm -f {quoted}.b64")


class ResilientTransport(Transport):
    """Breaker + retry protection around one cached backend transport.

    ``TransportManager.for_host`` hands these out, so single-host callers
    (nursery spawns, deploys, ad-hoc ``check_output``) share the same
    per-host failure streaks and open-circuit fast-fail as the
    ``run_on_all`` fan-out. All base-class helpers (``check_output``,
    ``test``, ``expand_remote_path``) funnel through the protected
    :meth:`run`; unknown attributes delegate to the wrapped backend so
    backend-specific surfaces (e.g. ``FakeTransport.on``) keep working.
    """

    def __init__(self, inner: Transport, resilience: TransportResilience) -> None:
        super().__init__(inner.host, inner.user)
        self.inner = inner
        self.timeout_s = getattr(inner, "timeout_s", Transport.timeout_s)
        self._resilience = resilience

    def run(self, command: str, timeout: Optional[float] = None,
            idempotent: bool = True) -> CommandResult:
        if not idempotent:
            # side-effecting command: breaker check only, never a re-issue
            breaker = self._resilience.breaker(self.hostname)
            if not breaker.allow():
                raise BreakerOpenError(self.hostname, breaker.retry_in_s(),
                                       breaker.consecutive_failures)
            try:
                result = self.inner.run(command, timeout=timeout)
            except TransportError:
                breaker.record_failure()
                raise
            breaker.record_success()
            return result
        return self._resilience.call(
            self.hostname,
            lambda attempt_timeout: self.inner.run(command, timeout=attempt_timeout),
            timeout=timeout,
        )

    def put_file(self, local_path: str, remote_path: str, mode: int = 0o755) -> None:
        # delegate so backend-native copy paths (scp) are preserved; the
        # many-step streaming fallback is not safely retryable as a unit
        self.inner.put_file(local_path, remote_path, mode=mode)

    def __getattr__(self, name: str):
        return getattr(self.inner, name)


_BACKENDS: Dict[str, Callable[..., Transport]] = {}


def register_backend(name: str, factory: Callable[..., Transport]) -> None:
    _BACKENDS[name] = factory


def make_transport(host: HostConfig, user: Optional[str] = None, config: Optional[Config] = None) -> Transport:
    config = config or get_config()
    backend = host.backend or config.ssh.default_backend
    try:
        factory = _BACKENDS[backend]
    except KeyError:
        raise TransportError(
            f"unknown transport backend {backend!r} for host {host.name} "
            f"(registered: {sorted(_BACKENDS)})"
        )
    return factory(host, user=user, config=config)


class TransportManager:
    """Caches per-(host, user) transports and fans commands out to many hosts
    in parallel (reference: SSHConnectionManager group client :21-46 +
    memoized per-user clients ssh.py:52-69; parallelism was gevent, here a
    thread pool with ``stop_on_errors=False`` semantics — per-host failures
    are isolated into the result map)."""

    def __init__(self, config: Optional[Config] = None, max_workers: int = 32,
                 resilience: Optional[TransportResilience] = None) -> None:
        self.config = config or get_config()
        #: per-host breakers + retry policy shared by the fan-out and cached
        #: single-host transports; injectable so tests/chaos harnesses drive
        #: it on a fake clock with a seeded rng
        self.resilience = resilience or TransportResilience(self.config)
        self._cache: Dict[Tuple[str, Optional[str]], Transport] = {}
        self._cache_lock = threading.Lock()
        self._closed = False
        # persistent pool: run_on_all fires once per monitor per ~2s tick, so
        # per-call executor construction would churn threads on the hot path
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="transport"
        )

    @property
    def hostnames(self) -> List[str]:
        return list(self.config.hosts)

    def add_host(self, host: HostConfig) -> None:
        """Dynamic host registration (reference SSHConnectionManager.add_host)."""
        self.config.hosts[host.name] = host

    def for_host(self, hostname: str, user: Optional[str] = None) -> Transport:
        key = (hostname, user)
        with self._cache_lock:
            if self._closed:
                raise TransportError(
                    "transport manager is closed; no transports available")
            if key not in self._cache:
                try:
                    host = self.config.hosts[hostname]
                except KeyError:
                    raise TransportError(f"unknown host {hostname!r}")
                self._cache[key] = ResilientTransport(
                    make_transport(host, user=user, config=self.config),
                    self.resilience,
                )
            return self._cache[key]

    def open_circuit_hosts(self) -> List[str]:
        """Hosts the resilience layer is currently refusing to contact —
        the set ``run_on_all`` skips and the job scheduler excludes."""
        return self.resilience.open_hosts()

    def invalidate(self, hostname: Optional[str] = None) -> None:
        with self._cache_lock:
            if hostname is None:
                self._cache.clear()
            else:
                for key in [k for k in self._cache if k[0] == hostname]:
                    del self._cache[key]

    def run_on_all(
        self,
        command: str,
        hostnames: Optional[List[str]] = None,
        timeout: Optional[float] = None,
    ) -> Dict[str, CommandResult]:
        """Parallel fan-out; failed hosts map to a synthetic non-zero result
        instead of raising (reference stop_on_errors=False, GPUMonitor.py:77)."""
        hostnames = hostnames if hostnames is not None else self.hostnames
        results: Dict[str, CommandResult] = {}
        if not hostnames:
            return results

        def _one(name: str) -> Tuple[CommandResult, str]:
            started = time.perf_counter()
            try:
                result = self.for_host(name).run(command, timeout=timeout)
                outcome = "ok" if result.ok else "error"
            except BreakerOpenError as exc:
                # open circuit: skipped outright — no round-trip happened, so
                # no latency observation; the synthetic result keeps the
                # per-host isolation contract (callers see a failure, fast)
                outcome = "circuit_open"
                result = CommandResult(
                    host=name, command=command, exit_code=255, stdout="",
                    stderr=str(exc),
                )
                _COMMANDS_TOTAL.labels(host=name, outcome=outcome).inc()
                return result, outcome
            except TransportError as exc:
                log.warning("host %s unreachable: %s", name, exc)
                outcome = "unreachable"
                result = CommandResult(
                    host=name, command=command, exit_code=255, stdout="", stderr=str(exc)
                )
            _COMMAND_SECONDS.labels(host=name).observe(
                time.perf_counter() - started)
            _COMMANDS_TOTAL.labels(host=name, outcome=outcome).inc()
            return result, outcome

        with get_tracer().span("transport.run_on_all", kind="transport",
                               hosts=len(hostnames)) as span:
            skipped = 0
            for name, (result, outcome) in zip(
                    hostnames, self._pool.map(_one, hostnames)):
                results[name] = result
                if outcome == "circuit_open":
                    skipped += 1
            failed = sum(1 for result in results.values() if not result.ok)
            span.attrs["failed"] = str(failed)
            span.attrs["circuit_open"] = str(skipped)
            if failed:
                span.status = "error"
        return results

    def close(self) -> None:
        """Shut down the pool AND drop cached transports: a closed manager
        must never hand out channels backed by a dead pool."""
        with self._cache_lock:
            self._closed = True
            self._cache.clear()
        self._pool.shutdown(wait=False)

    def test_all_connections(self) -> Dict[str, bool]:
        """Startup connectivity probe (reference TensorHiveManager.test_ssh:47-69)."""
        statuses = {}
        for name, result in self.run_on_all("uname").items():
            statuses[name] = result.ok
            if not result.ok:
                log.error("connectivity test failed for %s: %s", name, result.stderr)
        return statuses


# ---------------------------------------------------------------------------
_manager: Optional[TransportManager] = None
_manager_lock = threading.Lock()


def get_transport_manager() -> TransportManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = TransportManager()
        return _manager


def set_transport_manager(manager: Optional[TransportManager]) -> None:
    global _manager
    with _manager_lock:
        _manager = manager
