"""ReservationVerifier: may this user hold this chip for this window?

Reference: tensorhive/core/utils/ReservationVerifier.py:6-115 — a reservation
is allowed iff its [start, end) interval is fully covered by the union of the
user's active restrictions that include the reserved resource, where each
restriction contributes its [starts_at, ends_at) window intersected with its
weekly schedules (interval-sweeping algorithm :7-44,46-89). When permissions
change, existing reservations are (un)cancelled to match
(update_user_reservations_statuses :91-115).
"""
from __future__ import annotations

from datetime import datetime, time, timedelta
from typing import List, Tuple

from ..db.models.reservation import Reservation
from ..db.models.resource import Resource
from ..db.models.user import User
from ..utils.timeutils import iso_utc, utcnow

Interval = Tuple[datetime, datetime]


def _merge(intervals: List[Interval]) -> List[Interval]:
    """Sort + coalesce overlapping/touching intervals."""
    merged: List[Interval] = []
    for start, end in sorted(intervals):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def _covers(intervals: List[Interval], start: datetime, end: datetime) -> bool:
    if start >= end:
        return True    # empty window: vacuously covered
    cursor = start
    for iv_start, iv_end in _merge(intervals):
        if iv_start > cursor:
            return False
        cursor = max(cursor, iv_end)
        if cursor >= end:
            return True
    return cursor >= end


def _schedule_windows(schedule, lo: datetime, hi: datetime) -> List[Interval]:
    """Expand one weekly schedule into concrete intervals inside [lo, hi]
    (reference sweep over days, ReservationVerifier.py:46-89). Overnight
    windows (hour_end < hour_start) roll past midnight."""
    windows: List[Interval] = []
    hour_start: time = schedule.parsed_hour_start
    hour_end: time = schedule.parsed_hour_end
    schedule_days = schedule.days
    day = (lo - timedelta(days=1)).date()  # back one day for overnight spill
    last = hi.date()
    while day <= last:
        if day.isoweekday() in schedule_days:
            start = datetime.combine(day, hour_start)
            end = datetime.combine(day, hour_end)
            if end <= start:
                end += timedelta(days=1)
            windows.append((start, end))
        day += timedelta(days=1)
    return windows


def restriction_intervals(restriction, lo: datetime, hi: datetime) -> List[Interval]:
    """Concrete allowed intervals a restriction contributes within [lo, hi]."""
    start = max(restriction.starts_at, lo)
    end = min(restriction.ends_at, hi) if restriction.ends_at is not None else hi
    if end <= start:
        return []
    schedules = restriction.schedules
    if not schedules:
        return [(start, end)]
    out: List[Interval] = []
    for schedule in schedules:
        for win_start, win_end in _schedule_windows(schedule, start, end):
            clipped = (max(win_start, start), min(win_end, end))
            if clipped[1] > clipped[0]:
                out.append(clipped)
    return out


def is_reservation_allowed(user: User, reservation: Reservation) -> bool:
    """Reference ReservationVerifier.is_reservation_allowed."""
    if user.has_role("admin"):
        return True
    resource = Resource.get_by_uid(reservation.resource_id)
    intervals: List[Interval] = []
    for restriction in user.get_restrictions():
        if not restriction.is_global:
            if resource is None:
                continue
            if resource.id not in [r.id for r in restriction.resources]:
                continue
        intervals.extend(
            restriction_intervals(restriction, reservation.start, reservation.end)
        )
    return _covers(intervals, reservation.start, reservation.end)


def reverify_user(user: User, allow_grant: bool = True, allow_revoke: bool = True) -> None:
    """Single sweep over the user's future reservations: cancel those no
    longer permitted (``allow_revoke``), un-cancel auto-cancelled ones that
    became permitted again (``allow_grant``). An un-cancel is skipped when
    the slot was re-booked meanwhile — re-activating it would raise a
    conflict mid-sweep and abort re-verification of the remaining rows."""
    now = utcnow()
    future = Reservation.where(
        "user_id = ? AND end > ?", [user.id, iso_utc(now)]
    )
    for reservation in future:
        allowed = is_reservation_allowed(user, reservation)
        if allow_grant and reservation.is_cancelled and allowed:
            if reservation.would_interfere():
                continue
            reservation.is_cancelled = False
            reservation.save()
        elif allow_revoke and not reservation.is_cancelled and not allowed:
            reservation.is_cancelled = True
            reservation.save()


def update_user_reservations_statuses(user: User, have_users_permissions_increased: bool) -> None:
    """Directional wrapper matching the reference's API
    (ReservationVerifier.update_user_reservations_statuses :91-115)."""
    reverify_user(
        user,
        allow_grant=have_users_permissions_increased,
        allow_revoke=not have_users_permissions_increased,
    )
