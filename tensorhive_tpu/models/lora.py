"""LoRA fine-tuning: low-rank adapters over frozen base weights.

TPU-idiomatic formulation: adapters live in their OWN pytree (the only
thing the optimizer sees — the base stays frozen bit-for-bit and is
closed over by the loss), and the forward "merges on the fly":
``W_eff = W + (alpha/rank)·A@B`` per target matrix before the standard
``TransformerLM.apply``. That keeps a single copy of the model math (no
per-layer adapter plumbing), lets XLA fuse the rank-r update into the
surrounding graph, and makes serving trivial — ``merge`` bakes the
adapters into a plain param tree that decode.py and the checkpointing
path treat like any other model.

Rides the existing sharded train step through ``make_train_step``'s
``loss_fn`` hook exactly like the MLM family (models/encoder.py); the
reference has no model layer at all (SURVEY.md §2), so this extends the
compute stack beyond it.

Reference pattern: LoRA (Hu et al., 2021) — re-derived; no code copied.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .transformer import Params, TransformerConfig, TransformerLM

#: which block matrices get adapters by default — q and v projections,
#: the original LoRA recipe's sweet spot
DEFAULT_TARGETS = ("wq", "wv")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def init_lora(key: jax.Array, params: Params,
              lora_config: LoraConfig) -> Dict[str, Any]:
    """Adapter pytree mirroring ``params['blocks']`` at the target
    matrices: A [in, r] gaussian with std 1/rank, B = 0 [r, out] — zero
    init on B makes the adapted model EXACTLY the base model at step 0."""
    blocks = []
    for block in params["blocks"]:
        matrices = sorted(name for name, leaf in block.items()
                          if hasattr(leaf, "ndim") and leaf.ndim == 2)
        adapters = {}
        for name in lora_config.targets:
            if name not in matrices:
                raise ValueError(f"no matrix {name!r} in block; targets "
                                 f"must be drawn from {matrices}")
            fan_in, fan_out = block[name].shape
            key, a_key = jax.random.split(key)
            adapters[name] = {
                "A": (jax.random.normal(a_key, (fan_in, lora_config.rank),
                                        jnp.float32)
                      * (1.0 / lora_config.rank)),
                "B": jnp.zeros((lora_config.rank, fan_out), jnp.float32),
            }
        blocks.append(adapters)
    return {"blocks": blocks}


def merge(params: Params, lora_params: Dict[str, Any],
          lora_config: LoraConfig) -> Params:
    """Bake adapters into a plain param tree: W + scale·A@B. The result is
    indistinguishable from a fully-finetuned model to every consumer
    (apply / decode.generate / checkpointing)."""
    merged = dict(params)
    merged["blocks"] = []
    for block, adapters in zip(params["blocks"], lora_params["blocks"]):
        new_block = dict(block)
        for name, ab in adapters.items():
            delta = (ab["A"] @ ab["B"]) * lora_config.scale
            new_block[name] = block[name] + delta.astype(block[name].dtype)
        merged["blocks"].append(new_block)
    return merged


def lora_loss(lora_params: Dict[str, Any], tokens: jax.Array,
              config: TransformerConfig, mesh=None, *,
              base_params: Params, lora_config: LoraConfig) -> jax.Array:
    """``loss_fn`` for make_train_step with the ADAPTERS as the trained
    pytree: the base is a closed-over constant (frozen — its gradient is
    never formed), the merge happens in-graph so autodiff reaches A/B
    through the effective weights. Use
    ``functools.partial(lora_loss, base_params=..., lora_config=...)``."""
    merged = merge(base_params, lora_params, lora_config)
    return TransformerLM.loss(merged, tokens, config, mesh=mesh)
