"""Bidirectional encoder family: masked-language-model training.

The decoder-only LM (transformer.py) and this encoder share every layer —
the ONLY architectural difference is ``config.causal=False``, which every
attention path (single-shard flash kernels, flash-ring sequence
parallelism, the pipelined trunk) already takes as a flag. What this
module adds is the MLM objective (BERT-style dynamic masking) and its
adapter into the sharded train step, so the full parallelism stack
(pp/dp/fsdp/tp/sp) trains encoders unchanged.

The reference has no model code at all (SURVEY.md §2: it launches
trainings); this extends the compute stack beyond it with a second model
family next to the causal LM.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .transformer import PRESETS, Params, TransformerConfig, TransformerLM

#: encoder presets mirror the LM geometries with bidirectional attention;
#: the top vocab id is reserved as the [MASK] token (mask_token_id below)
ENCODER_PRESETS = {
    name: dataclasses.replace(PRESETS[name], causal=False)
    for name in ("tiny", "t2t-base", "t2t-big")
}


def mask_token_id(config: TransformerConfig) -> int:
    """[MASK] is the top vocab id — no vocab surgery, the embedding row
    already exists; data pipelines must simply not emit it as text."""
    return config.vocab_size - 1


def mask_tokens(
    key: jax.Array,
    tokens: jax.Array,                  # [B, L] int32
    config: TransformerConfig,
    mask_ratio: float = 0.15,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """BERT-style dynamic masking: select ``mask_ratio`` of positions; of
    those 80% become [MASK], 10% a uniform random token, 10% keep the
    original (the model must still predict at kept positions — it cannot
    trust its input). Returns (inputs, targets, mask) with mask [B, L]
    bool over the SELECTED positions. Fully shape-static (jit/shard-safe):
    the realized mask count is binomial around the ratio, exactly the
    original dynamic-masking recipe."""
    select_key, op_key, rand_key = jax.random.split(key, 3)
    uniform = jax.random.uniform(select_key, tokens.shape)
    mask = uniform < mask_ratio
    op = jax.random.uniform(op_key, tokens.shape)
    random_tokens = jax.random.randint(rand_key, tokens.shape, 0,
                                       config.vocab_size, dtype=tokens.dtype)
    inputs = jnp.where(mask & (op < 0.8), mask_token_id(config), tokens)
    inputs = jnp.where(mask & (op >= 0.8) & (op < 0.9), random_tokens, inputs)
    return inputs, tokens, mask


def mlm_loss(
    params: Params,
    inputs: jax.Array,                  # [B, L] int32 (post-masking)
    targets: jax.Array,                 # [B, L] int32 (originals)
    mask: jax.Array,                    # [B, L] bool — selected positions
    config: TransformerConfig,
    mesh=None,
) -> jax.Array:
    """Cross-entropy over the selected positions only, mean per masked
    token (f32). Shares the LM loss's memory machinery
    (transformer._lse_minus_target / _chunked_ce behind the same
    _loss_chunk threshold), so encoder training holds the batch sizes the
    causal LM does instead of OOMing on a full [N, vocab] logits buffer."""
    from .transformer import _chunked_ce, _loss_chunk, _lse_minus_target

    n_tokens = targets.shape[0] * targets.shape[1]
    count = jnp.maximum(jnp.sum(mask), 1)
    chunk = _loss_chunk(n_tokens, config, mesh)
    if chunk:
        x = TransformerLM.apply_trunk(params, inputs, config, mesh=mesh)
        total = _chunked_ce(
            x.reshape(n_tokens, -1), targets.reshape(n_tokens),
            params["w_lm_head"], config.dtype, chunk,
            weights_flat=mask.reshape(n_tokens))
        return total / count
    logits = TransformerLM.apply(params, inputs, config, mesh=mesh)
    per_token = _lse_minus_target(logits, targets) * mask.astype(jnp.float32)
    return jnp.sum(per_token) / count


def pack_mlm_batch(key: jax.Array, tokens: jax.Array,
                   config: TransformerConfig,
                   mask_ratio: float = 0.15) -> jax.Array:
    """(inputs, targets, mask) stacked into ONE int32 [B, 3, L] array so
    the masked batch rides the existing train-step plumbing (donated
    buffers, batch sharding over dp×fsdp on the leading dim, grad
    accumulation) without widening its interface."""
    inputs, targets, mask = mask_tokens(key, tokens, config, mask_ratio)
    return jnp.stack([inputs, targets, mask.astype(inputs.dtype)], axis=1)


def mlm_loss_packed(params: Params, packed: jax.Array,
                    config: TransformerConfig, mesh=None) -> jax.Array:
    """``loss_fn`` adapter for train.make_train_step: unpack [B, 3, L] and
    compute the masked CE."""
    inputs, targets, mask = packed[:, 0], packed[:, 1], packed[:, 2]
    return mlm_loss(params, inputs, targets, mask.astype(bool), config,
                    mesh=mesh)


@functools.lru_cache(maxsize=8)
def _mlm_eval_loss_fn(config: TransformerConfig, mesh):
    """Jitted masked loss per (config, mesh) — same cache discipline as
    decode._eval_loss_fn: a fresh jit per evaluate call would recompile
    the whole model on every periodic eval."""
    return jax.jit(functools.partial(mlm_loss_packed, config=config,
                                     mesh=mesh))


def mlm_evaluate(
    params: Params,
    config: TransformerConfig,
    batches,
    num_batches: int,
    mesh=None,
    *,
    seed: int = 0,
    mask_ratio: float = 0.15,
):
    """Held-out MLM evaluation: masks each batch with a seed-deterministic
    pattern and averages the masked CE — the encoder counterpart of
    decode.evaluate, with the SAME signature shape and contract (positional
    mesh 5th, loud error on an exhausted iterator). Returns
    {'loss', 'pseudo_perplexity', 'batches'}; pseudo-perplexity is
    exp(masked CE), the standard encoder proxy for held-out fit."""
    if config.causal:
        raise ValueError("mlm_evaluate needs an encoder config "
                         "(causal=False); score causal LMs with "
                         "decode.evaluate")
    if num_batches < 1:
        raise ValueError(f"num_batches must be >= 1, got {num_batches}")
    loss_fn = _mlm_eval_loss_fn(config, mesh)
    key = jax.random.PRNGKey(seed)
    # on-device accumulation, one host sync after the loop — same TH-J
    # discipline as decode.evaluate (a per-batch float() would block the
    # dispatch pipeline once per batch)
    total = jnp.zeros((), jnp.float32)
    for index in range(num_batches):
        try:
            tokens = next(batches)
        except StopIteration:
            raise ValueError(
                f"batches iterator exhausted at batch {index} of "
                f"{num_batches}") from None
        packed = pack_mlm_batch(jax.random.fold_in(key, index), tokens,
                                config, mask_ratio)
        total = total + loss_fn(params, packed)
    mean = float(total) / num_batches
    # math.exp on the already-synced host float: jnp.exp here would be a
    # SECOND device dispatch + blocking sync after the loss sync above
    try:
        pseudo_perplexity = math.exp(mean)
    except OverflowError:           # diverged eval; jnp.exp returned inf too
        pseudo_perplexity = float("inf")
    return {"loss": mean,
            "pseudo_perplexity": pseudo_perplexity,
            "batches": num_batches}


def init_encoder(key: jax.Array, config: Optional[TransformerConfig] = None,
                 preset: str = "t2t-base") -> Tuple[Params, TransformerConfig]:
    """Convenience: (params, config) for an encoder preset."""
    if config is None:
        config = ENCODER_PRESETS[preset]
    if config.causal:
        raise ValueError("encoder config must have causal=False")
    return TransformerLM.init(key, config), config
