"""Decoder-only transformer LM, TPU-first.

Design choices map straight onto the hardware (task brief + scaling-book
recipe), not onto any reference code (the reference has no model code at
all — it launches external t2t/DeepSpeech trainings):

* **bf16 everywhere the MXU is involved**: params are kept in f32 master
  copies, cast to bf16 for matmuls; logits/loss/softmax in f32.
* **Static shapes, no data-dependent control flow** — one jit trace.
* **RoPE** positions (no learned position table to shard), pre-RMSNorm,
  SwiGLU MLP — the standard modern decoder block, all MXU-dense.
* **Parallelism-aware**: every weight carries logical axes (see
  parallel/mesh.py _PARAM_LOGICAL) so the same model runs pure-dp, fsdp,
  megatron-tp, and ring-attention sp by choosing a mesh; attention runs
  through the pallas flash kernel on single-shard sequences and through
  ring attention when the sequence is sharded over ``sp``.
* **jax.checkpoint** on each block so activation memory trades against
  HBM bandwidth (remat is the TPU-default tradeoff for long sequences).

Pure-functional: params are a plain dict pytree; ``TransformerLM`` is a
namespace of ``init`` / ``apply`` / ``loss`` staticmethods.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.flash_attention import flash_attention
from ..parallel.ring import ring_attention

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 1408            # ~8/3 * d_model, SwiGLU sizing
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16   # activation/matmul dtype
    rope_theta: float = 10_000.0
    remat: bool = True
    #: what ``remat`` recomputes: "block" checkpoints whole blocks (max
    #: memory savings, backward re-runs EVERYTHING incl. the VPU-bound flash
    #: forward); "mlp" checkpoints only the MLP half — attention activations
    #: (q/k/v, flash out+lse residuals) stay saved, so the backward never
    #: re-executes the flash kernels, at ~2.5× the activation footprint of
    #: "block". Measured on v5e t2t-big b8×s4096: the difference between
    #: MFU 0.16 and ≥0.25 (VERDICT r2 weak #1c)
    remat_policy: str = "block"
    #: use the pallas flash kernel for non-sp attention
    use_flash: bool = True
    #: token-chunk size for the memory-efficient CE loss (0 disables); only
    #: engaged when the per-device logits shard would exceed the device
    #: threshold (_chunk_threshold_bytes: ~0.7× HBM on TPU, 2 GiB where the
    #: device can't report memory), so fitting runs keep the fused fast path
    loss_chunk_tokens: int = 16_384

    #: grouped-query attention: number of K/V heads (None = n_heads, MHA).
    #: Shrinks wk/wv and — the real win — the decode KV cache by
    #: n_heads/n_kv_heads; Q heads share K/V heads in groups.
    n_kv_heads: Optional[int] = None

    #: pipeline microbatches when the mesh has pp > 1 (0 = one per stage);
    #: more microbatches shrink the (pp-1)/(M+pp-1) bubble at the cost of
    #: smaller per-step matmuls
    pp_microbatches: int = 0

    #: attention direction: True = autoregressive LM (next-token loss,
    #: KV-cache decode); False = bidirectional encoder (models/encoder.py
    #: MLM family) — every attention path (flash, ring, pipelined) takes
    #: the flag, decode requires causal=True
    causal: bool = True

    @property
    def d_head(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads or self.n_heads
        assert self.n_heads % kv == 0, "n_heads must be a multiple of n_kv_heads"
        return kv


#: named sizes; "t2t-base" mirrors tensor2tensor transformer_base
#: (6 layers / d512 / 8 heads / ff2048 — the reference's benchmark config)
PRESETS: Dict[str, TransformerConfig] = {
    "tiny": TransformerConfig(vocab_size=512, d_model=64, n_heads=4, n_layers=2,
                              d_ff=176, max_seq_len=256),
    "t2t-base": TransformerConfig(vocab_size=32_000, d_model=512, n_heads=8,
                                  n_layers=6, d_ff=2048, max_seq_len=2048),
    "t2t-big": TransformerConfig(vocab_size=32_000, d_model=1024, n_heads=16,
                                 n_layers=6, d_ff=4096, max_seq_len=2048),
    "1b": TransformerConfig(vocab_size=32_000, d_model=2048, n_heads=16,
                            n_layers=16, d_ff=5632, max_seq_len=4096),
    # Llama-7B-class dims (BASELINE.json config 5: multi-slice 7B on
    # 2x v5p-32). GQA-8 and SwiGLU d_ff match the Llama-2 generation; far
    # too big to materialize on one chip or in CI — exercised at the shape
    # level (eval_shape + tree_shardings) and by the multichip dryrun path
    "7b": TransformerConfig(vocab_size=32_000, d_model=4096, n_heads=32,
                            n_layers=32, d_ff=11_008, max_seq_len=4096,
                            n_kv_heads=8, remat=True, remat_policy="mlp"),
}


#: fallback threshold for the chunked CE path when the device can't report
#: its memory (CPU/interpret): engage once full logits would exceed 2 GiB
CHUNKED_LOSS_THRESHOLD_BYTES = 2 << 30


@functools.lru_cache(maxsize=1)
def _chunk_threshold_bytes() -> int:
    """Engage chunking only when the full-logits path would genuinely
    pressure HBM: measured on v5e, the full path at 8.6 GB logits (b64×s1024
    ×32k vocab) is ~6% faster than chunked recompute, so chunking must not
    trigger while the fused path still fits: b64's 8.6 GB logits run fine on
    a 16 GB v5e (~0.62 of bytes_limit) while b128's 17 GB cannot, so 0.7
    keeps the measured-good config on the fast path with the flip safely
    below the OOM point."""
    device = jax.devices()[0]
    try:
        return int(device.memory_stats()["bytes_limit"] * 0.7)
    except (AttributeError, KeyError, TypeError, RuntimeError,
            NotImplementedError):
        # the known no-memory-introspection shapes: memory_stats absent
        # (AttributeError), unimplemented (RuntimeError incl. XlaRuntimeError,
        # NotImplementedError), returns None (TypeError) or lacks the key
        # (KeyError) — all fall through to the platform defaults below
        pass
    if device.platform == "tpu":
        # some TPU runtimes don't expose memory_stats; assume the smallest
        # current-generation HBM (16 GiB, v5e) — underestimating on larger
        # chips merely engages chunking earlier than strictly needed
        return int((16 << 30) * 0.7)
    return CHUNKED_LOSS_THRESHOLD_BYTES


def _loss_chunk(n_tokens: int, config: "TransformerConfig", mesh) -> int:
    """Token-chunk size for the memory-efficient CE path, or 0 for the
    fused full-logits path. The batch dim shards over dp×fsdp and the
    vocab dim of the LM head (hence of the logits) over tp
    (parallel/mesh.py batch_sharding + _PARAM_LOGICAL), so what pressures
    HBM is each device's logits SHARD — compared against the per-device
    threshold. The chunk shrinks to a divisor of n_tokens (gcd) so
    awkward batch sizes still chunk instead of silently falling back to
    the full-logits path and OOMing; a tiny gcd means tiny matmuls, but
    this branch only engages where the full path would not fit at all —
    slow-but-runs beats OOM. Shared by the LM and MLM losses."""
    if not config.loss_chunk_tokens:
        return 0
    logits_shards = 1
    if mesh is not None:
        logits_shards = (mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
                         * mesh.shape.get("tp", 1))
    logits_bytes = n_tokens * config.vocab_size * 4 // logits_shards
    if logits_bytes <= _chunk_threshold_bytes():
        return 0
    return math.gcd(n_tokens, config.loss_chunk_tokens)


def _lse_minus_target(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-token cross entropy as logsumexp − target_logit [..., L]: never
    materializes the log-probability tensor — the gather and reduction
    fuse into the logits consumer. Shared by the LM loss, the chunked CE
    and the MLM loss (models/encoder.py)."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    return lse - target_logit


def _chunked_ce(x_flat: jax.Array, targets_flat: jax.Array, w_head: jax.Array,
                dtype: Any, chunk_tokens: int,
                weights_flat: Optional[jax.Array] = None) -> jax.Array:
    """Sum of weight·(logsumexp − target_logit) over all tokens, computed
    one token-chunk at a time (``weights_flat`` None = unweighted; the MLM
    loss passes its mask). ``jax.checkpoint`` on the chunk body means the
    backward pass recomputes each chunk's logits instead of storing them —
    peak memory is one [chunk, vocab] f32 buffer either direction."""
    num_chunks = x_flat.shape[0] // chunk_tokens
    x_chunks = x_flat.reshape(num_chunks, chunk_tokens, -1)
    t_chunks = targets_flat.reshape(num_chunks, chunk_tokens)
    if weights_flat is None:
        weights_flat = jnp.ones((x_flat.shape[0],), jnp.float32)
    w_chunks = weights_flat.astype(jnp.float32).reshape(
        num_chunks, chunk_tokens)

    @jax.checkpoint
    def one_chunk(args):
        x_blk, t_blk, w_blk = args
        logits = jnp.dot(x_blk.astype(dtype), w_head.astype(dtype),
                         preferred_element_type=jnp.float32)
        return jnp.sum(_lse_minus_target(logits, t_blk) * w_blk)

    return jnp.sum(jax.lax.map(one_chunk, (x_chunks, t_chunks, w_chunks)))


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over the last dim of [B, L, H, D]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # [B,L,1,D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rotated = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.reshape(x.shape).astype(x.dtype)


def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    norm = jnp.asarray(x, jnp.float32)
    norm = norm * jax.lax.rsqrt(jnp.mean(norm * norm, axis=-1, keepdims=True) + 1e-6)
    return (norm * scale.astype(jnp.float32)).astype(x.dtype)


class TransformerLM:
    """init / apply / loss over a plain param pytree."""

    # -- init ---------------------------------------------------------------
    @staticmethod
    def init(key: jax.Array, config: TransformerConfig) -> Params:
        keys = iter(jax.random.split(key, 4 + 7 * config.n_layers))

        def dense(key, fan_in, *shape):
            return (jax.random.normal(key, shape, jnp.float32)
                    * (1.0 / math.sqrt(fan_in)))

        d, h, dh, f = (config.d_model, config.n_heads, config.d_head, config.d_ff)
        kv = config.kv_heads
        params: Params = {
            "tok_embed": jax.random.normal(next(keys), (config.vocab_size, d),
                                           jnp.float32) * 0.02,
            "final_norm": {"scale": jnp.ones((d,), jnp.float32)},
            "w_lm_head": dense(next(keys), d, d, config.vocab_size),
            "blocks": [],
        }
        for _ in range(config.n_layers):
            params["blocks"].append({
                "attn_norm": {"scale": jnp.ones((d,), jnp.float32)},
                "mlp_norm": {"scale": jnp.ones((d,), jnp.float32)},
                "wq": dense(next(keys), d, d, h * dh),
                "wk": dense(next(keys), d, d, kv * dh),
                "wv": dense(next(keys), d, d, kv * dh),
                "wo": dense(next(keys), h * dh, h * dh, d),
                "w_in": dense(next(keys), d, d, f),
                "w_gate": dense(next(keys), d, d, f),
                "w_out": dense(next(keys), f, f, d),
            })
        return params

    # -- forward ------------------------------------------------------------
    @staticmethod
    def block_attn_half(x, block, config: TransformerConfig, positions,
                        attend, layer_index: Optional[int] = None) -> jax.Array:
        """Attention half of a block: pre-norm QKV + rope + attend + output
        projection, residual added.

        ``attend`` is called as ``attend(q, k, v)`` — or, when the caller
        passes ``layer_index``, as ``attend(q, k, v, layer_index)``: cache-
        updating strategies (models/decode.py) write each layer's K/V into
        one full 5-D buffer and need the layer coordinate, without building
        a fresh closure per layer."""
        dtype = config.dtype
        h = _rmsnorm(x, block["attn_norm"]["scale"])
        b, l, d = h.shape
        q = (h @ block["wq"].astype(dtype)).reshape(b, l, config.n_heads,
                                                    config.d_head)
        k = (h @ block["wk"].astype(dtype)).reshape(b, l, config.kv_heads,
                                                    config.d_head)
        v = (h @ block["wv"].astype(dtype)).reshape(b, l, config.kv_heads,
                                                    config.d_head)
        q = _rope(q, positions, config.rope_theta)
        k = _rope(k, positions, config.rope_theta)
        attn = (attend(q, k, v) if layer_index is None
                else attend(q, k, v, layer_index))
        attn = attn.reshape(b, l, config.n_heads * config.d_head)
        return x + attn @ block["wo"].astype(dtype)

    @staticmethod
    def block_mlp_half(x, block, config: TransformerConfig) -> jax.Array:
        """SwiGLU MLP half of a block, residual added."""
        dtype = config.dtype
        h = _rmsnorm(x, block["mlp_norm"]["scale"])
        gated = jax.nn.silu(h @ block["w_gate"].astype(dtype)) * (
            h @ block["w_in"].astype(dtype)
        )
        return x + gated @ block["w_out"].astype(dtype)

    @staticmethod
    def block_forward(x, block, config: TransformerConfig, positions,
                      attend, layer_index: Optional[int] = None) -> jax.Array:
        """One transformer block (pre-norm attention + SwiGLU MLP). The
        SINGLE copy of the block math — training (apply_trunk) and cached
        decoding (models/decode.py apply_step) both route through it with
        their own ``attend(q, k, v) -> [B, L, H, Dh]`` strategy, so the
        architectures cannot drift apart. ``layer_index`` (optional) is
        forwarded to ``attend`` for strategies that index a stacked
        all-layers KV cache — see block_attn_half."""
        x = TransformerLM.block_attn_half(x, block, config, positions, attend,
                                          layer_index=layer_index)
        return TransformerLM.block_mlp_half(x, block, config)

    @staticmethod
    def apply_trunk(
        params: Params,
        tokens: jax.Array,                  # [B, L] int32
        config: TransformerConfig,
        mesh=None,
        positions: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Everything before the LM head: returns normed activations
        [B, L, d_model] (activation dtype, post final rmsnorm)."""
        dtype = config.dtype
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape
            )
        if mesh is not None and (mesh.shape.get("tp", 1) > 1
                                 or mesh.shape.get("fsdp", 1) > 1):
            # iota/one-hot embedding (the MaxText idiom): with the table
            # sharded (vocab→tp, embed→fsdp) a gather forward forces a
            # scatter-add backward whose updates the partitioner can only
            # produce by FULLY REPLICATING dx ("Involuntary full
            # rematerialization", VERDICT r2 weak #3); as a matmul both
            # directions partition natively (and TPU scatter is slow anyway)
            onehot = jax.nn.one_hot(tokens, config.vocab_size, dtype=dtype)
            x = onehot @ params["tok_embed"].astype(dtype)
        else:
            x = params["tok_embed"].astype(dtype)[tokens]

        sp_sharded = mesh is not None and "sp" in getattr(mesh, "axis_names", ()) \
            and mesh.shape["sp"] > 1
        from ..parallel.pipeline import pp_enabled

        if pp_enabled(mesh):
            return TransformerLM._apply_trunk_pipelined(
                params, x, positions, config, mesh, sp_sharded=sp_sharded)

        def pin(t):
            # pin activations to their canonical sharding between blocks:
            # without the explicit constraint the partitioner propagates a
            # transposed-mesh sharding backward out of the remat'd block and
            # falls into "Involuntary full rematerialization" replication on
            # every block boundary (VERDICT r2 weak #3)
            if mesh is None:
                return t
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.lax.with_sharding_constraint(
                t, NamedSharding(mesh, P(("dp", "fsdp"), "sp", None)))

        def attend(q, k, v):
            # GQA is native everywhere: the single-shard kernels and the
            # flash-ring body both read KV head h // group through their
            # BlockSpec index maps (no expanded K/V copy; the ring also
            # rotates group× smaller KV blocks over ICI). The dense
            # fallbacks expand internally.
            if sp_sharded:
                return ring_attention(q, k, v, mesh=mesh,
                                      causal=config.causal)
            if config.use_flash:
                return flash_attention(q, k, v, causal=config.causal)
            from ..ops.flash_attention import reference_attention

            return reference_attention(q, k, v, causal=config.causal)

        if config.remat and config.remat_policy == "mlp":
            # selective remat: attention activations (incl. the flash
            # out+lse custom-vjp residuals) stay saved — the backward
            # recomputes ONLY the MLP half. The flash forward is VPU-bound
            # (softmax passes over S² elements); rerunning it in the
            # backward is the single largest remat cost at long sequence
            mlp_fn = jax.checkpoint(
                lambda x, block: TransformerLM.block_mlp_half(x, block, config))

            def block_fn(x, block):
                x = TransformerLM.block_attn_half(x, block, config, positions,
                                                  attend)
                return pin(mlp_fn(x, block))
        else:
            def block_fn(x, block):
                return pin(TransformerLM.block_forward(x, block, config,
                                                       positions, attend))

            if config.remat:
                block_fn = jax.checkpoint(block_fn)
        x = pin(x)
        for block in params["blocks"]:
            x = block_fn(x, block)

        return _rmsnorm(x, params["final_norm"]["scale"])

    @staticmethod
    def _apply_trunk_pipelined(params, x, positions,
                               config: TransformerConfig, mesh,
                               sp_sharded: bool = False) -> jax.Array:
        """Blocks as a ``pp``-stage GPipe pipeline (parallel/pipeline.py):
        stage params are the per-layer dicts stacked and sharded over the
        pp axis; dp/fsdp/tp stay automatic inside each stage, so the flash
        kernels and megatron splits run exactly as in the unpipelined
        path. With ``sp_sharded`` the pipeline's shard_map goes manual over
        {pp, sp} and each stage attends via the manual ring body
        (ring_attention_local) — sequence parallelism INSIDE pipeline
        stages, no nested shard_map."""
        from ..parallel.pipeline import pipeline_apply, stack_blocks

        def attend(q, k, v):
            if sp_sharded:
                from ..parallel.ring import ring_attention_local

                return ring_attention_local(q, k, v, "sp",
                                            mesh.shape["sp"],
                                            causal=config.causal)
            # inside the pipeline's manual region, pallas only on real TPU:
            # interpret-mode pallas is unsupported under vma tracking (see
            # parallel/pipeline.py) — CI/CPU takes the XLA oracle
            if config.use_flash and jax.default_backend() == "tpu":
                return flash_attention(q, k, v, causal=config.causal)
            from ..ops.flash_attention import reference_attention

            return reference_attention(q, k, v, causal=config.causal)

        def apply_layer(block, x_mb, pos_mb):
            return TransformerLM.block_forward(x_mb, block, config, pos_mb,
                                               attend)

        if config.remat:
            apply_layer = jax.checkpoint(apply_layer)
        x = pipeline_apply(
            stack_blocks(params["blocks"]), x, positions, mesh, apply_layer,
            num_microbatches=config.pp_microbatches,
            seq_axis="sp" if sp_sharded else None)
        return _rmsnorm(x, params["final_norm"]["scale"])

    @staticmethod
    def apply(
        params: Params,
        tokens: jax.Array,                  # [B, L] int32
        config: TransformerConfig,
        mesh=None,
        positions: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Returns logits [B, L, vocab] (f32)."""
        x = TransformerLM.apply_trunk(params, tokens, config, mesh=mesh,
                                      positions=positions)
        # LM head: bf16 operands, f32 MXU accumulation. A full-f32 matmul
        # here runs at ~1/4 MXU throughput and this [*, d]x[d, vocab] matmul
        # is the single largest in the model (~40% of forward FLOPs for
        # t2t-base); bf16-in/f32-out is the standard LM-head precision.
        return jnp.dot(x.astype(config.dtype),
                       params["w_lm_head"].astype(config.dtype),
                       preferred_element_type=jnp.float32)

    # -- loss ---------------------------------------------------------------
    @staticmethod
    def loss(
        params: Params,
        tokens: jax.Array,                  # [B, L+1] int32 (inputs+shifted)
        config: TransformerConfig,
        mesh=None,
    ) -> jax.Array:
        """Next-token cross-entropy, mean over tokens (f32)."""
        if not config.causal:
            # bidirectional attention lets position p see token p+1 — its
            # own target; the next-token loss would collapse toward zero
            # while training a copy-through model. Same loud refusal as
            # decode.generate/evaluate.
            raise ValueError(
                "TransformerLM.loss is the autoregressive objective; this "
                "config is a bidirectional encoder (causal=False) — train "
                "it with models/encoder.mlm_loss_packed")
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        n_tokens = targets.shape[0] * targets.shape[1]
        chunk = _loss_chunk(n_tokens, config, mesh)
        if chunk:
            # chunked head+loss: the [N, vocab] f32 logits tensor is the
            # largest buffer of a training step (17 GB at b128×s1024×32k —
            # past a v5e's whole HBM). Computing lse/target-logit one token
            # chunk at a time with per-chunk recompute in the backward keeps
            # peak memory at one chunk's logits, unlocking batch sizes the
            # full-logits path cannot hold. Costs one extra head matmul in
            # the backward (~+2/6 of head FLOPs).
            x = TransformerLM.apply_trunk(params, inputs, config, mesh=mesh)
            total = _chunked_ce(
                x.reshape(n_tokens, -1), targets.reshape(n_tokens),
                params["w_lm_head"], config.dtype, chunk)
            return total / n_tokens
        logits = TransformerLM.apply(params, inputs, config, mesh=mesh)
        return jnp.mean(_lse_minus_target(logits, targets))

    @staticmethod
    def param_count(params: Params) -> int:
        return sum(leaf.size for leaf in jax.tree_util.tree_leaves(params))


def train_flops_per_token(config: TransformerConfig, seq_len: int,
                          remat: bool = False) -> float:
    """Analytic model FLOPs per trained token (matmuls only — norms/rope/
    softmax are bandwidth, not MXU FLOPs). Used for MFU reporting.

    Per token, forward: Q+O projections 4·D², K+V projections 4·D·Hkv·Dh
    (GQA-shrunk when n_kv_heads < n_heads), SwiGLU 6·D·F, attention core
    QKᵀ + PV at 2·2·S·D — halved by causality for the LM, full-width for
    bidirectional encoders (config.causal=False) — LM head 2·D·V.
    Training ≈ 3× forward (one forward + two backward matmuls per forward
    matmul); remat re-runs each block's forward once more."""
    d, f, v = config.d_model, config.d_ff, config.vocab_size
    kv_dim = config.kv_heads * config.d_head
    attn_core = (2 if config.causal else 4) * seq_len * d
    per_layer = 4 * d * d + 4 * d * kv_dim + 6 * d * f + attn_core
    fwd = config.n_layers * per_layer + 2 * d * v
    factor = 4.0 if remat else 3.0
    # remat does not recompute the LM head (it is outside the blocks)
    if remat:
        return factor * config.n_layers * per_layer + 3.0 * 2 * d * v
    return factor * fwd
