"""Autoregressive decoding with a KV cache, and held-out evaluation.

The inference side of the training stack (no reference counterpart — the
reference manages clusters, it has no model code at all). TPU-first design:

* **one jitted scan, static shapes** — the cache is a fixed
  [layers, B, max_len, KV_HEADS, D] buffer updated IN PLACE with one
  ``dynamic_update_slice`` at ``(layer, 0, position, 0, 0)`` per layer;
  prefill + generation run as a single on-device ``lax.scan`` (position,
  prompt length and scan start traced, step count static), so one compiled
  executable covers the whole generation with no per-token host dispatch
  (measured 24× over a python token loop on a tunneled v5e).
* **donated buffers** — the cache, token buffer and PRNG key are donated
  across the ``_prefill_cache`` → ``_generate_on_device`` boundary
  (``donate_argnames``), so XLA aliases the multi-hundred-MB cache between
  the two executables and across scan steps instead of copying it.
* **shape-bucketed prefill** — prompt lengths pad up to power-of-two
  buckets (``_prefill_bucket``; real length stays a traced operand that
  masks the padded cache writes), so serving mixed-length prompts compiles
  O(log S) executables instead of one per distinct length; compiles vs.
  shape-cache reuses are counted in ``tpuhive_decode_compile_total``.
* **decode attention is a masked grouped dot over the cache** — single-token
  decode is HBM-bandwidth-bound (reading K/V), not FLOP-bound, so a pallas
  kernel buys nothing here; GQA attends against the unexpanded cache.
"""
from __future__ import annotations

import functools
import math
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..observability import get_registry
from .transformer import (
    Params,
    TransformerConfig,
    TransformerLM,
    _rmsnorm,
)


class KVCache(NamedTuple):
    k: jax.Array          # [layers, B, max_len, H, Dh]
    v: jax.Array          # [layers, B, max_len, H, Dh]


class QuantKVCache(NamedTuple):
    """Int8-quantized paged KV cache (``kv_quant = on``): same page layout
    as :class:`KVCache`'s paged form but one byte per cell, with per-(page,
    kv_head) f32 scales in side-arrays indexed by the SAME physical page
    ids the page tables resolve (ops/kv_quant.py; docs/SERVING.md
    "Quantized KV pages"). The serving bodies branch on the cache pytree's
    type at trace time, so ``kv_quant=off`` engines never trace a single
    quantization op — the byte-identical rollback contract."""
    k: jax.Array          # [layers, pages, page_size, Hkv, Dh] int8
    v: jax.Array          # [layers, pages, page_size, Hkv, Dh] int8
    k_scale: jax.Array    # [layers, pages, Hkv] f32
    v_scale: jax.Array    # [layers, pages, Hkv] f32


def init_cache(config: TransformerConfig, batch: int,
               max_len: Optional[int] = None) -> KVCache:
    """Cache is [layers, B, max_len, KV_HEADS, Dh] — with GQA the cache is
    n_heads/n_kv_heads times smaller, the point of grouped-query decode."""
    max_len = max_len or config.max_seq_len
    shape = (config.n_layers, batch, max_len, config.kv_heads, config.d_head)
    return KVCache(k=jnp.zeros(shape, config.dtype),
                   v=jnp.zeros(shape, config.dtype))


def _decode_attend(q, k_cache, v_cache, position):
    """q: [B,1,H,Dh]; caches [B,S,Hkv,Dh]; attend to positions <= position.

    GQA attends DIRECTLY against the unexpanded cache via a grouped einsum
    (q reshaped to [B,1,Hkv,G,Dh]) — materializing an expanded K/V copy per
    step would restore the MHA-sized HBM read this cache layout exists to
    avoid. Head convention matches the training expand (jnp.repeat): full
    head i shares kv head i // group."""
    batch, _, heads, d_head = q.shape
    kv_heads = k_cache.shape[2]
    group = heads // kv_heads
    scale = d_head ** -0.5
    q_grouped = q.reshape(batch, 1, kv_heads, group, d_head)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", q_grouped, k_cache,
                        preferred_element_type=jnp.float32) * scale
    key_positions = jax.lax.iota(jnp.int32, k_cache.shape[1])
    mask = key_positions[None, None, None, None, :] <= position
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v_cache.dtype),
                     v_cache, preferred_element_type=jnp.float32)
    return out.reshape(batch, 1, heads, d_head).astype(q.dtype)


def _paged_attend(q, k_pages, v_pages, page_table, positions,
                  use_kernel: bool = False,
                  interpret: Optional[bool] = None,
                  mesh=None, shard_heads: bool = False,
                  k_scales=None, v_scales=None):
    """Paged-cache decode attention, two dispatches behind one signature
    (the ``use_flash`` pattern — serving/engine.py prefill):

    * **XLA gather path** (``use_kernel=False``, the reference): gather
      each slot's pages into logical order, then the SAME masked grouped
      math as :func:`_decode_attend`. f32-EXACT against the contiguous
      engine and ``decode.generate`` (test_paging.py) — but it
      materializes a ``[S, max_pages*page_size, Hkv, Dh]`` copy of every
      slot's pages each step (the gather tax docs/PERF.md measures).
    * **Fused pallas kernel** (``use_kernel=True``,
      :func:`~tensorhive_tpu.ops.paged_attention.paged_attention`): the
      grid walks the page table and streams K/V straight from their
      physical pages with online-softmax accumulation — no gathered
      intermediate. Within ~1e-7 of the gather path in f32 (accumulation
      order; tolerance rationale in docs/SERVING.md), greedy tokens
      pinned identical.

    q: [S,1,H,Dh]; ``k_pages``/``v_pages`` are one layer of the paged cache
    [num_pages, page_size, Hkv, Dh]; ``page_table`` [S, max_pages] holds
    physical page indices and ``positions`` [S] each slot's current
    position — both traced operands (page assignment must never be a
    shape, or every admission would recompile).

    The gather reconstructs a contiguous per-slot view: logical position p
    of slot s lives at ``(page_table[s, p // page_size], p % page_size)``,
    so reshaping the gathered pages lays keys out in logical order and the
    ``<= position`` mask inside :func:`_decode_attend` applies unchanged.
    Entries still pointing at the trash page hold other sequences' (or
    garbage) K/V, but every such logical position is > the slot's position
    — masked to -1e30, exp-underflowed to exactly 0.0 in the softmax (the
    kernel applies the identical mask per page block).

    ``mesh``/``shard_heads`` (serving mesh, docs/SERVING.md "Multi-chip
    serving"): under a sharded engine the XLA gather path needs nothing —
    GSPMD partitions it off the cache's NamedSharding, bit-identically —
    but the pallas custom call MUST NOT be left to GSPMD (it partitions
    the grid blindly and the per-shard page tables would index physical
    pages the shard does not hold: silently wrong output, pinned by the
    mesh parity tests). The kernel therefore runs under ``shard_map``:
    with ``shard_heads`` each tp shard runs the UNCHANGED grid on its
    local head slice (q heads and kv_heads both split over tp — contiguous
    head blocks keep the ``i // group`` GQA mapping aligned per shard)
    against the full page pool, page tables/positions replicated; without
    it (the GQA replication guard, tp not dividing both head counts) the
    kernel runs fully replicated — correct, and the cache layout the
    engine picks for the kernel dispatch matches these specs.

    ``k_scales``/``v_scales`` ([num_pages, Hkv] f32, ``kv_quant = on``):
    the pages are int8 and attention consumes ``dequant(stored)`` — the
    gather path dequantizes the gathered run (ops/kv_quant.py), the
    kernel dequantizes per page in VMEM right after the DMA with the
    scales riding as scalar-prefetch operands, so the int8 read also
    halves-or-quarters the decode step's HBM traffic (docs/SERVING.md
    "Quantized KV pages")."""
    if use_kernel:
        from ..ops.paged_attention import paged_attention

        kernel = functools.partial(paged_attention, interpret=interpret,
                                   k_scales=k_scales, v_scales=v_scales)
        if mesh is not None:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            head_spec = (P(None, None, "tp", None) if shard_heads else P())
            if k_scales is not None:
                # scales shard like their pages' kv_heads axis: split over
                # tp exactly when the K/V head axis is, replicated otherwise
                scale_spec = P(None, "tp") if shard_heads else P()

                def quant_kernel(q, k, v, table, positions, ks, vs):
                    return paged_attention(q, k, v, table, positions,
                                           interpret=interpret,
                                           k_scales=ks, v_scales=vs)

                return shard_map(
                    quant_kernel, mesh=mesh,
                    in_specs=(head_spec, head_spec, head_spec, P(), P(),
                              scale_spec, scale_spec),
                    out_specs=head_spec, check_rep=False,
                )(q, k_pages, v_pages, page_table, positions,
                  k_scales, v_scales)
            return shard_map(
                kernel, mesh=mesh,
                in_specs=(head_spec, head_spec, head_spec, P(), P()),
                out_specs=head_spec, check_rep=False,
            )(q, k_pages, v_pages, page_table, positions)
        return kernel(q, k_pages, v_pages, page_table, positions)
    num_slots, max_pages = page_table.shape
    page_size = k_pages.shape[1]
    window = max_pages * page_size
    if k_scales is not None:
        from ..ops.kv_quant import dequant_gather

        k = dequant_gather(k_pages, k_scales, page_table, q.dtype)
        v = dequant_gather(v_pages, v_scales, page_table, q.dtype)
    else:
        k = k_pages[page_table].reshape(num_slots, window,
                                        *k_pages.shape[2:])
        v = v_pages[page_table].reshape(num_slots, window,
                                        *v_pages.shape[2:])
    return _decode_attend(q, k, v,
                          positions[:, None, None, None, None])


def apply_step(
    params: Params,
    token: jax.Array,               # [B] int32 — the token AT `position`
    cache: KVCache,
    position: jax.Array,            # scalar int32
    config: TransformerConfig,
) -> Tuple[jax.Array, KVCache]:
    """One decode step: logits for the NEXT position + updated cache.

    Routes through TransformerLM.block_forward (the single copy of the
    block math) with a cache-updating attend strategy, so training and
    decoding cannot architecturally drift. Each layer writes its [B,1,H,Dh]
    K/V directly into the full 5-D buffer with ONE dynamic_update_slice at
    (layer, 0, position, 0, 0) — the seed version sliced a per-layer view
    and re-``jnp.stack``ed all layers every step, an O(layers·B·S·Hkv·Dh)
    rebuild per token that XLA cannot reliably alias away inside a scan."""
    dtype = config.dtype
    x = params["tok_embed"].astype(dtype)[token][:, None, :]   # [B,1,D]
    positions = jnp.full((token.shape[0], 1), position, jnp.int32)
    cache_k, cache_v = cache.k, cache.v

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, k.astype(cache_k.dtype)[None],
            (layer, 0, position, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, v.astype(cache_v.dtype)[None],
            (layer, 0, position, 0, 0))
        return _decode_attend(q, cache_k[layer], cache_v[layer], position)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, positions, attend,
                                        layer_index=layer_index)
    x = _rmsnorm(x, params["final_norm"]["scale"])
    logits = jnp.dot(x[:, 0].astype(dtype), params["w_lm_head"].astype(dtype),
                     preferred_element_type=jnp.float32)
    return logits, KVCache(k=cache_k, v=cache_v)


def _prefill_body(params, prompt_head, cache, config, real_len=None):
    """Write K/V for prompt positions 0..real_len-1 into the cache in ONE
    batched pass — thousands of serial single-token cache updates for a long
    prompt collapse into one full-width trunk pass (flash attention over the
    prompt, no LM head). ``prompt_head`` may be right-padded up to a shape
    bucket; ``real_len`` (traced) zero-masks the padded K/V writes, and
    causal attention already keeps every real position exact regardless of
    what sits to its right. Cache contents match the sequential path to
    float accumulation-order tolerance — batched vs per-token matmuls cannot
    be bit-equal (tested at 2e-4 in
    test_decode.py::test_batched_prefill_cache_matches_sequential)."""
    from .transformer import flash_attention

    dtype = config.dtype
    batch, width = prompt_head.shape
    x = params["tok_embed"].astype(dtype)[prompt_head]
    positions = jnp.broadcast_to(jnp.arange(width, dtype=jnp.int32),
                                 (batch, width))
    if real_len is None:
        valid = None                    # exact-width call: nothing padded
    else:
        valid = (jnp.arange(width, dtype=jnp.int32)
                 < real_len)[None, :, None, None]
    cache_k, cache_v = cache.k, cache.v

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        write_k, write_v = k, v
        if valid is not None:
            write_k = jnp.where(valid, k, 0)
            write_v = jnp.where(valid, v, 0)
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, write_k.astype(cache_k.dtype)[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, write_v.astype(cache_v.dtype)[None], (layer, 0, 0, 0, 0))
        # GQA runs natively in the kernel (KV head h // group via the
        # BlockSpec index maps) — no expanded K/V copy
        return flash_attention(q, k, v, causal=True)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, positions, attend,
                                        layer_index=layer_index)
    return KVCache(k=cache_k, v=cache_v)


#: the serving path donates the cache (XLA aliases the buffer into the
#: output instead of copying it); the undonated twin exists for callers
#: that reuse one filled cache across calls (bench steady-state timing)
_prefill_cache = functools.partial(jax.jit, static_argnames=("config",),
                                   donate_argnames=("cache",))(_prefill_body)
_prefill_cache_undonated = functools.partial(
    jax.jit, static_argnames=("config",))(_prefill_body)


def _generate_body(params, tokens, cache, key, prompt_len, temperature,
                   start, config, num_steps, sampling, top_k):
    """The whole prefill+generate loop as ONE lax.scan on device. A python
    per-token loop pays the host→device dispatch latency every step — ~80 ms
    per token over a tunneled link vs ~3.5 ms for the step itself; the scan
    leaves the device busy end to end (measured 24× on t2t-base).

    Only shape-determining values are static (num_steps, the sampling MODE
    and top_k); prompt_len, temperature and the scan start position are
    traced operands, so — with prefill shapes bucketed — varying prompt
    lengths, temperatures and seeds all reuse one compiled executable per
    (batch, bucket) pair."""
    total = tokens.shape[1]

    def step(carry, index):
        tokens, cache, key = carry
        position = start + index
        current = jax.lax.dynamic_slice_in_dim(tokens, position, 1, axis=1)[:, 0]
        logits, cache = apply_step(params, current, cache, position, config)

        def pick(operands):
            # branch outputs cast to tokens.dtype INSIDE the branches:
            # lax.cond requires identical output dtypes and argmax/
            # categorical default to the platform int, which diverges from
            # an int64 tokens array under jax_enable_x64
            logits, key = operands
            if not sampling:
                return jnp.argmax(logits, axis=-1).astype(tokens.dtype), key
            scaled = logits / temperature
            if top_k is not None:
                # only the k-th largest value is needed for the filter:
                # lax.top_k is O(V·log k) over the vocab where the seed's
                # full jnp.sort paid O(V·log V) every sampled step
                kth = jax.lax.top_k(scaled, top_k)[0][:, -1][:, None]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            key, sample_key = jax.random.split(key)
            chosen = jax.random.categorical(sample_key, scaled, axis=-1)
            return chosen.astype(tokens.dtype), key

        def prefill(operands):
            # next token comes from the prompt: skip the top-k/sample work
            # entirely and leave the PRNG stream untouched
            logits, key = operands
            upcoming = jax.lax.dynamic_slice_in_dim(
                tokens, jnp.minimum(position + 1, total - 1), 1, axis=1)[:, 0]
            return upcoming.astype(tokens.dtype), key

        chosen, key = jax.lax.cond(position + 1 < prompt_len, prefill, pick,
                                   (logits, key))
        tokens = jax.lax.dynamic_update_slice(
            tokens, chosen[:, None], (0, position + 1))
        return (tokens, cache, key), None

    # return the WHOLE final carry: donation is implemented as XLA
    # input-output aliasing, so each donated operand needs a same-shaped
    # output to alias into — returning only tokens would leave the cache
    # and key donations unusable (and the final cache is the natural hook
    # for continuation decoding)
    carry, _ = jax.lax.scan(
        step, (tokens, cache, key), jnp.arange(num_steps))
    return carry


_GENERATE_STATICS = ("config", "num_steps", "sampling", "top_k")
#: serving path: tokens/cache/key are donated — the scan carry and the
#: prefill output alias in place instead of being copied into the executable
_generate_on_device = functools.partial(
    jax.jit, static_argnames=_GENERATE_STATICS,
    donate_argnames=("tokens", "cache", "key"))(_generate_body)
_generate_on_device_undonated = functools.partial(
    jax.jit, static_argnames=_GENERATE_STATICS)(_generate_body)


#: floor for prefill shape buckets — below this, distinct executables are
#: cheap enough that finer buckets would only fragment the compile cache
PREFILL_BUCKET_FLOOR = 16


def _prefill_bucket(length: int, cap: int,
                    floor: int = PREFILL_BUCKET_FLOOR) -> int:
    """Pad a prefill width up to the next power of two (min ``floor``),
    capped at ``cap`` (the widest head max_seq_len admits) so the top
    bucket never allocates past the model's sequence budget."""
    bucket = max(floor, 1 << max(0, length - 1).bit_length())
    return min(bucket, max(length, cap))


_compile_seen: set = set()


def _count_compile(fn: str, fingerprint: tuple) -> str:
    """Count decode-path executable compiles (miss = first time this shape
    fingerprint is dispatched in-process, mirroring jax's jit cache) vs.
    shape-cache reuses (hit) in ``tpuhive_decode_compile_total``; returns
    the event so per-request callers (the serving ledger) can attribute
    THIS dispatch without re-deriving the fingerprint."""
    event = "hit" if fingerprint in _compile_seen else "miss"
    _compile_seen.add(fingerprint)
    get_registry().counter(
        "tpuhive_decode_compile_total",
        "decode-path executables: miss = new shape compiled, "
        "hit = shape-cache reuse",
        labels=("fn", "event")).labels(fn=fn, event=event).inc()
    return event


def generate(
    params: Params,
    config: TransformerConfig,
    prompt: jax.Array,              # [B, P] int32
    max_new_tokens: int,
    temperature: float = 0.0,       # 0 = greedy
    top_k: Optional[int] = None,
    seed: int = 0,
    batched_prefill: bool = True,
    bucket_prompt: bool = True,
    donate: bool = True,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations: returns [B, P+N] int32.

    Requires an autoregressive model: a bidirectional encoder config
    (``causal=False``, models/encoder.py) has no valid left-to-right
    factorization to sample from.

    With ``batched_prefill`` (default) the prompt's K/V enter the cache via
    ONE full-width trunk pass and the decode scan runs only the generated
    positions — a 1-2k-token prompt costs one batched forward instead of
    thousands of serial cache updates (measured on v5e, t2t-base,
    1024-token prompt + 32 new: 168 ms vs 692 ms host-synced — 4.1×).

    ``bucket_prompt`` (default) pads the prefill width up to a power-of-two
    bucket (``_prefill_bucket``) and sizes the token/cache buffers off the
    bucket, so mixed-length prompts at one (batch, max_new_tokens) compile
    O(log S) executables instead of one per distinct length — the real
    prompt length stays a traced operand (it masks padded cache writes and
    steers the prompt-vs-sample branch), so only the bucket is baked in.
    Compiles vs. reuses are observable in ``tpuhive_decode_compile_total``.
    ``batched_prefill=False`` keeps the round-2 behavior of one executable
    for all prompt lengths at the same total (and never buckets).

    ``donate`` hands the token/cache/key buffers to XLA (`donate_argnames`)
    so the prefill output aliases into the generate executable instead of
    being copied — at t2t-big scale the cache is hundreds of MB per call.
    Donation changes buffer ownership, never values (pinned exactly in f32
    by test_decode.py::test_donated_generate_matches_undonated); pass
    ``donate=False`` only when profiling against held cache references.

    All paths are logically identical (tested exactly in f32); in bf16 a
    batched and a sequential matmul differ in accumulation order, so greedy
    argmax near-ties (untrained weights) can pick different tokens — same
    caveat as any batch-size change."""
    if not config.causal:
        raise ValueError("generate() needs an autoregressive model; this "
                         "config is a bidirectional encoder (causal=False)")
    batch, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > config.max_seq_len:
        raise ValueError(
            f"prompt+new = {total} exceeds max_seq_len {config.max_seq_len}")
    if top_k is not None and not 0 < top_k <= config.vocab_size:
        # checked up-front (not only on sampling steps): jnp's index
        # clamping would otherwise silently disable the filter
        raise ValueError(
            f"top_k must be in (0, {config.vocab_size}], got {top_k}")
    sampling = temperature > 0.0
    prefilling = batched_prefill and prompt_len > 1
    head_width = prompt_len - 1
    if prefilling and bucket_prompt:
        # cap: the widest head any prompt at this max_new could have, so
        # the top bucket never allocates past max_seq_len
        head_width = _prefill_bucket(
            prompt_len - 1, config.max_seq_len - max_new_tokens - 1)
    # buffers sized off the BUCKET: padding lives at positions the scan
    # either overwrites before attending or never attends at all (mask is
    # `<= position`), so bucketed output is exact, not approximate
    buffer_total = head_width + 1 + max_new_tokens if prefilling else total
    num_steps = max_new_tokens if prefilling else total - 1

    cache = init_cache(config, batch, max_len=buffer_total)
    key = jax.random.PRNGKey(seed)
    tokens = jnp.concatenate(
        [prompt, jnp.zeros((batch, buffer_total - prompt_len), prompt.dtype)],
        axis=1)
    start = 0
    if prefilling:
        # prefill positions 0..P-2; the scan's first step consumes the
        # token at P-1 and emits the first generated position
        head = prompt[:, :prompt_len - 1]
        if head_width > prompt_len - 1:
            head = jnp.pad(head, ((0, 0), (0, head_width - (prompt_len - 1))))
        _count_compile("prefill",
                       ("prefill", config, batch, head_width, buffer_total,
                        donate))
        prefill_fn = _prefill_cache if donate else _prefill_cache_undonated
        cache = prefill_fn(params, head, cache, config,
                           jnp.int32(prompt_len - 1))
        start = prompt_len - 1
    _count_compile("generate",
                   ("generate", config, batch, buffer_total, num_steps,
                    sampling, top_k if sampling else None, donate))
    generate_fn = (_generate_on_device if donate
                   else _generate_on_device_undonated)
    out, _, _ = generate_fn(
        params, tokens, cache, key, jnp.int32(prompt_len),
        jnp.float32(temperature if sampling else 1.0), jnp.int32(start),
        config=config, num_steps=num_steps, sampling=sampling,
        top_k=top_k if sampling else None)
    return out[:, :total]


@functools.lru_cache(maxsize=8)
def _eval_loss_fn(config: TransformerConfig, mesh):
    """Jitted loss per (config, mesh) — a fresh jit per evaluate() call
    would recompile the whole model on every periodic eval."""
    return jax.jit(functools.partial(TransformerLM.loss, config=config,
                                     mesh=mesh))


def evaluate(
    params: Params,
    config: TransformerConfig,
    batches,
    num_batches: int,
    mesh=None,
) -> Dict[str, float]:
    """Mean held-out loss/perplexity over ``num_batches`` from an iterator
    of [B, L+1] token arrays (e.g. data.prefetch_to_device)."""
    if not config.causal:
        # next-token CE through bidirectional attention would see each
        # target in its own input — perplexity collapses toward 1,
        # silently wrong rather than loudly refused
        raise ValueError("evaluate() scores next-token perplexity, which "
                         "needs an autoregressive model; this config is a "
                         "bidirectional encoder (causal=False)")
    if num_batches < 1:
        raise ValueError(f"num_batches must be >= 1, got {num_batches}")
    loss_fn = _eval_loss_fn(config, mesh)
    # accumulate ON DEVICE: a float() per batch would force a blocking
    # device->host sync each iteration, serializing the async dispatch
    # pipeline (TH-J); one conversion after the loop syncs once
    total = jnp.zeros((), jnp.float32)
    for index in range(num_batches):
        try:
            tokens = next(batches)
        except StopIteration:
            raise ValueError(
                f"batches iterator exhausted at batch {index} of "
                f"{num_batches}") from None
        total = total + loss_fn(params, tokens)
    mean = float(total) / num_batches
    # math.exp on the already-synced host float: jnp.exp here would be a
    # SECOND device dispatch + blocking sync after the loss sync above
    try:
        perplexity = math.exp(mean)
    except OverflowError:           # diverged eval; jnp.exp returned inf too
        perplexity = float("inf")
    return {"loss": mean, "perplexity": perplexity, "batches": num_batches}
