"""Autoregressive decoding with a KV cache, and held-out evaluation.

The inference side of the training stack (no reference counterpart — the
reference manages clusters, it has no model code at all). TPU-first design:

* **one jitted scan, static shapes** — the cache is a fixed
  [layers, B, max_len, KV_HEADS, D] buffer updated with
  ``dynamic_update_slice``; prefill + generation run as a single on-device
  ``lax.scan`` (position and prompt length traced, total length static), so
  one compiled executable covers the whole generation with no per-token
  host dispatch (measured 24× over a python token loop on a tunneled v5e).
* **decode attention is a masked grouped dot over the cache** — single-token
  decode is HBM-bandwidth-bound (reading K/V), not FLOP-bound, so a pallas
  kernel buys nothing here; GQA attends against the unexpanded cache.
"""
from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .transformer import (
    Params,
    TransformerConfig,
    TransformerLM,
    _rmsnorm,
)


class KVCache(NamedTuple):
    k: jax.Array          # [layers, B, max_len, H, Dh]
    v: jax.Array          # [layers, B, max_len, H, Dh]


def init_cache(config: TransformerConfig, batch: int,
               max_len: Optional[int] = None) -> KVCache:
    """Cache is [layers, B, max_len, KV_HEADS, Dh] — with GQA the cache is
    n_heads/n_kv_heads times smaller, the point of grouped-query decode."""
    max_len = max_len or config.max_seq_len
    shape = (config.n_layers, batch, max_len, config.kv_heads, config.d_head)
    return KVCache(k=jnp.zeros(shape, config.dtype),
                   v=jnp.zeros(shape, config.dtype))


def _decode_attend(q, k_cache, v_cache, position):
    """q: [B,1,H,Dh]; caches [B,S,Hkv,Dh]; attend to positions <= position.

    GQA attends DIRECTLY against the unexpanded cache via a grouped einsum
    (q reshaped to [B,1,Hkv,G,Dh]) — materializing an expanded K/V copy per
    step would restore the MHA-sized HBM read this cache layout exists to
    avoid. Head convention matches the training expand (jnp.repeat): full
    head i shares kv head i // group."""
    batch, _, heads, d_head = q.shape
    kv_heads = k_cache.shape[2]
    group = heads // kv_heads
    scale = d_head ** -0.5
    q_grouped = q.reshape(batch, 1, kv_heads, group, d_head)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", q_grouped, k_cache,
                        preferred_element_type=jnp.float32) * scale
    key_positions = jax.lax.iota(jnp.int32, k_cache.shape[1])
    mask = key_positions[None, None, None, None, :] <= position
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v_cache.dtype),
                     v_cache, preferred_element_type=jnp.float32)
    return out.reshape(batch, 1, heads, d_head).astype(q.dtype)


def apply_step(
    params: Params,
    token: jax.Array,               # [B] int32 — the token AT `position`
    cache: KVCache,
    position: jax.Array,            # scalar int32
    config: TransformerConfig,
) -> Tuple[jax.Array, KVCache]:
    """One decode step: logits for the NEXT position + updated cache.

    Routes through TransformerLM.block_forward (the single copy of the
    block math) with a cache-updating attend strategy, so training and
    decoding cannot architecturally drift."""
    dtype = config.dtype
    x = params["tok_embed"].astype(dtype)[token][:, None, :]   # [B,1,D]
    positions = jnp.full((token.shape[0], 1), position, jnp.int32)
    new_k, new_v = [], []
    for layer_index, block in enumerate(params["blocks"]):
        def attend(q, k, v, _layer=layer_index):
            k_cache = jax.lax.dynamic_update_slice(
                cache.k[_layer], k.astype(cache.k.dtype), (0, position, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                cache.v[_layer], v.astype(cache.v.dtype), (0, position, 0, 0))
            new_k.append(k_cache)
            new_v.append(v_cache)
            return _decode_attend(q, k_cache, v_cache, position)

        x = TransformerLM.block_forward(x, block, config, positions, attend)
    x = _rmsnorm(x, params["final_norm"]["scale"])
    logits = jnp.dot(x[:, 0].astype(dtype), params["w_lm_head"].astype(dtype),
                     preferred_element_type=jnp.float32)
    cache = KVCache(k=jnp.stack(new_k), v=jnp.stack(new_v))
    return logits, cache


@functools.partial(jax.jit, static_argnames=("config",))
def _prefill_cache(params, prompt_head, cache, config):
    """Write K/V for prompt positions 0..L0-1 into the cache in ONE batched
    pass — thousands of serial single-token cache updates for a long prompt
    collapse into one full-width trunk pass (flash attention over the
    prompt, no LM head). Cache contents match the sequential path to float
    accumulation-order tolerance — batched vs per-token matmuls cannot be
    bit-equal (tested at 2e-4 in
    test_decode.py::test_batched_prefill_cache_matches_sequential)."""
    from .transformer import flash_attention

    dtype = config.dtype
    batch, l0 = prompt_head.shape
    x = params["tok_embed"].astype(dtype)[prompt_head]
    positions = jnp.broadcast_to(jnp.arange(l0, dtype=jnp.int32), (batch, l0))
    new_k, new_v = [], []

    for layer_index, block in enumerate(params["blocks"]):
        def attend(q, k, v, _layer=layer_index):
            new_k.append(jax.lax.dynamic_update_slice(
                cache.k[_layer], k.astype(cache.k.dtype), (0, 0, 0, 0)))
            new_v.append(jax.lax.dynamic_update_slice(
                cache.v[_layer], v.astype(cache.v.dtype), (0, 0, 0, 0)))
            # GQA runs natively in the kernel (KV head h // group via the
            # BlockSpec index maps) — no expanded K/V copy
            return flash_attention(q, k, v, causal=True)

        x = TransformerLM.block_forward(x, block, config, positions, attend)
    return KVCache(k=jnp.stack(new_k), v=jnp.stack(new_v))


@functools.partial(
    jax.jit, static_argnames=("config", "total", "start", "sampling", "top_k"))
def _generate_on_device(params, tokens, cache, key, prompt_len, temperature,
                        config, total, sampling, top_k, start=0):
    """The whole prefill+generate loop as ONE lax.scan on device. A python
    per-token loop pays the host→device dispatch latency every step — ~80 ms
    per token over a tunneled link vs ~3.5 ms for the step itself; the scan
    leaves the device busy end to end (measured 24× on t2t-base).

    Only shape-determining values are static (total, the sampling MODE and
    top_k); prompt_len and temperature are traced operands, so varying
    prompt lengths or temperatures reuse one compiled executable."""

    def step(carry, position):
        tokens, cache, key = carry
        current = jax.lax.dynamic_slice_in_dim(tokens, position, 1, axis=1)[:, 0]
        logits, cache = apply_step(params, current, cache, position, config)

        def pick(operands):
            # branch outputs cast to tokens.dtype INSIDE the branches:
            # lax.cond requires identical output dtypes and argmax/
            # categorical default to the platform int, which diverges from
            # an int64 tokens array under jax_enable_x64
            logits, key = operands
            if not sampling:
                return jnp.argmax(logits, axis=-1).astype(tokens.dtype), key
            scaled = logits / temperature
            if top_k is not None:
                kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            key, sample_key = jax.random.split(key)
            chosen = jax.random.categorical(sample_key, scaled, axis=-1)
            return chosen.astype(tokens.dtype), key

        def prefill(operands):
            # next token comes from the prompt: skip the vocab-wide sort/
            # sample work entirely and leave the PRNG stream untouched
            logits, key = operands
            upcoming = jax.lax.dynamic_slice_in_dim(
                tokens, jnp.minimum(position + 1, total - 1), 1, axis=1)[:, 0]
            return upcoming.astype(tokens.dtype), key

        chosen, key = jax.lax.cond(position + 1 < prompt_len, prefill, pick,
                                   (logits, key))
        tokens = jax.lax.dynamic_update_slice(
            tokens, chosen[:, None], (0, position + 1))
        return (tokens, cache, key), None

    (tokens, _, _), _ = jax.lax.scan(
        step, (tokens, cache, key), jnp.arange(start, total - 1))
    return tokens


def generate(
    params: Params,
    config: TransformerConfig,
    prompt: jax.Array,              # [B, P] int32
    max_new_tokens: int,
    temperature: float = 0.0,       # 0 = greedy
    top_k: Optional[int] = None,
    seed: int = 0,
    batched_prefill: bool = True,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations: returns [B, P+N] int32.

    Requires an autoregressive model: a bidirectional encoder config
    (``causal=False``, models/encoder.py) has no valid left-to-right
    factorization to sample from.

    With ``batched_prefill`` (default) the prompt's K/V enter the cache via
    ONE full-width trunk pass and the decode scan runs only the generated
    positions — a 1-2k-token prompt costs one batched forward instead of
    thousands of serial cache updates (measured on v5e, t2t-base,
    1024-token prompt + 32 new: 168 ms vs 692 ms host-synced — 4.1×). The
    executable then specializes on the prompt length (the TPU prefill
    idiom: shape-bucketed compiles); ``batched_prefill=False`` keeps the
    round-2 behavior of one executable for all prompt lengths at the same
    total. The two paths are logically identical (tested exactly in f32);
    in bf16 a batched and a sequential matmul differ in accumulation
    order, so greedy argmax near-ties (untrained weights) can pick
    different tokens — same caveat as any batch-size change."""
    if not config.causal:
        raise ValueError("generate() needs an autoregressive model; this "
                         "config is a bidirectional encoder (causal=False)")
    batch, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > config.max_seq_len:
        raise ValueError(
            f"prompt+new = {total} exceeds max_seq_len {config.max_seq_len}")
    if top_k is not None and not 0 < top_k <= config.vocab_size:
        # checked up-front (not only on sampling steps): jnp's index
        # clamping would otherwise silently disable the filter
        raise ValueError(
            f"top_k must be in (0, {config.vocab_size}], got {top_k}")
    cache = init_cache(config, batch, max_len=total)
    key = jax.random.PRNGKey(seed)
    tokens = jnp.concatenate(
        [prompt, jnp.zeros((batch, max_new_tokens), prompt.dtype)], axis=1)
    sampling = temperature > 0.0
    start = 0
    if batched_prefill and prompt_len > 1:
        # prefill positions 0..P-2; the scan's first step consumes the
        # token at P-1 and emits the first generated position
        cache = _prefill_cache(params, prompt[:, :prompt_len - 1], cache,
                               config)
        start = prompt_len - 1
    return _generate_on_device(
        params, tokens, cache, key, jnp.int32(prompt_len),
        jnp.float32(temperature if sampling else 1.0),
        config=config, total=total, sampling=sampling,
        top_k=top_k if sampling else None, start=start)


@functools.lru_cache(maxsize=8)
def _eval_loss_fn(config: TransformerConfig, mesh):
    """Jitted loss per (config, mesh) — a fresh jit per evaluate() call
    would recompile the whole model on every periodic eval."""
    return jax.jit(functools.partial(TransformerLM.loss, config=config,
                                     mesh=mesh))


def evaluate(
    params: Params,
    config: TransformerConfig,
    batches,
    num_batches: int,
    mesh=None,
) -> Dict[str, float]:
    """Mean held-out loss/perplexity over ``num_batches`` from an iterator
    of [B, L+1] token arrays (e.g. data.prefetch_to_device)."""
    if not config.causal:
        # next-token CE through bidirectional attention would see each
        # target in its own input — perplexity collapses toward 1,
        # silently wrong rather than loudly refused
        raise ValueError("evaluate() scores next-token perplexity, which "
                         "needs an autoregressive model; this config is a "
                         "bidirectional encoder (causal=False)")
    if num_batches < 1:
        raise ValueError(f"num_batches must be >= 1, got {num_batches}")
    loss_fn = _eval_loss_fn(config, mesh)
    # accumulate ON DEVICE: a float() per batch would force a blocking
    # device->host sync each iteration, serializing the async dispatch
    # pipeline (TH-J); one conversion after the loop syncs once
    total = jnp.zeros((), jnp.float32)
    for index in range(num_batches):
        try:
            tokens = next(batches)
        except StopIteration:
            raise ValueError(
                f"batches iterator exhausted at batch {index} of "
                f"{num_batches}") from None
        total = total + loss_fn(params, tokens)
    mean = float(total) / num_batches
    return {"loss": mean, "perplexity": float(jnp.exp(mean)),
            "batches": num_batches}
