"use strict";
/* reservations: week time-grid with drag-to-reserve.
   Reference: ReservationsOverview.vue + FullCalendar*.vue — FullCalendar
   agendaWeek with GPU multi-select, drag-select to create, click to
   edit/cancel. Rebuilt on a plain CSS grid: 7 day columns x 48 half-hour
   slots; events are absolutely positioned; drag is mousedown->mousemove->
   mouseup snapped to 30-minute slots. */

const SLOT_PX = 22, SLOT_MIN = 30;
let calStart = startOfWeek(new Date());
let calResources = [];                        // cached /resources
let calSelected = null;                       // Set of selected uids
let calEvents = [];                           // cached reservations for week
let calDrag = null;                           // {dayIdx, fromSlot, toSlot}
let calView = localStorage.getItem("tpuhive-cal-view") || "week";
if (calView === "month") {
  // a persisted month view must anchor to the 1st of the CURRENT month —
  // startOfWeek(today) lands in the previous month during the first
  // partial week, skewing the header and the 42-day matrix
  const now = new Date();
  calStart = new Date(now.getFullYear(), now.getMonth(), 1);
}

function startOfWeek(d) {
  d = new Date(d); d.setHours(0, 0, 0, 0);
  d.setDate(d.getDate() - (d.getDay() + 6) % 7);  // Monday
  return d;
}
function resourceHue(uid) {
  let acc = 0;
  for (const ch of uid) acc = (acc * 31 + ch.charCodeAt(0)) % 360;
  return acc;
}
function loadSelected() {
  try {
    const saved = JSON.parse(localStorage.getItem("tpuhive-cal") || "null");
    if (Array.isArray(saved)) return new Set(saved);
  } catch (e) {}
  return null;
}

function renderCalendar(main) {
  main.innerHTML = `<div class="card">
    <div class="row">
      <div class="respick">
        <button class="ghost" onclick="toggleResPicker()">Chips
          <span id="respick-count"></span> ▾</button>
        <div class="panel" id="respick-panel" style="display:none"></div>
      </div>
      <button class="ghost" onclick="calShift(-1)">‹ prev</button>
      <b id="cal-range"></b>
      <button class="ghost" onclick="calShift(1)">next ›</button>
      <button class="ghost" onclick="calToday()">today</button>
      <button class="ghost" id="cal-view-btn" onclick="calToggleView()"></button>
      <span style="flex:1"></span>
      <span class="muted">drag on the grid to reserve</span>
      <button class="primary" onclick="openReservationDialog()">New reservation</button>
    </div>
    <div id="cal" class="tgrid-wrap" style="margin-top:1rem"></div>
  </div>
  <div id="usage-card"></div>
  <dialog id="res-dialog"></dialog>`;
  drawCalendar();
  drawUsage();
}

/* usage accounting (reference: UsageLoggingService averages persisted onto
   the reservation row): recently finished reservations + their recorded
   utilization */
async function drawUsage() {
  const el = document.getElementById("usage-card");
  if (!el) return;
  const now = new Date();
  const weekAgo = new Date(now - 7 * 864e5);
  let past;
  try {
    past = await api(`/reservations?start=${weekAgo.toISOString()}&end=${now.toISOString()}`);
  } catch (e) { return; }
  const finished = past.filter(r => new Date(r.end) <= now && !r.isCancelled);
  if (!finished.length) { el.innerHTML = ""; return; }
  finished.sort((a, b) => new Date(b.end) - new Date(a.end));
  el.innerHTML = `<div class="card">
    <h3 style="margin:0 0 .5rem">Usage — last 7 days</h3>
    <table><tr><th>reservation</th><th>chip</th><th>ended</th>
      <th>avg duty</th><th>avg HBM</th></tr>
    ${finished.slice(0, 12).map(r => `<tr>
      <td>${esc(r.title)} <span class="muted">#${r.id}</span></td>
      <td class="muted">${esc(r.resourceId)}</td>
      <td class="muted">${fmtDt(r.end)}</td>
      <td>${r.dutyCycleAvg != null ? r.dutyCycleAvg + "%" : "—"}</td>
      <td>${r.hbmUtilAvg != null ? r.hbmUtilAvg + "%" : "—"}</td>
    </tr>`).join("")}</table>
    ${finished.length > 12 ? `<p class="muted">…and ${finished.length - 12} more</p>` : ""}
  </div>`;
}
function calShift(direction) {
  if (calView === "month") calStart.setMonth(calStart.getMonth() + direction);
  else calStart.setDate(calStart.getDate() + direction * 7);
  drawCalendar();
}
function calToday() {
  calStart = calView === "month"
    ? new Date(new Date().getFullYear(), new Date().getMonth(), 1)
    : startOfWeek(new Date());
  drawCalendar();
}
/* month view (reference FullCalendar month mode — round-2 shipped only the
   week grid): compact month matrix, a day click drills into its week */
function calToggleView() {
  calView = calView === "month" ? "week" : "month";
  localStorage.setItem("tpuhive-cal-view", calView);
  calStart = calView === "month"
    ? new Date(calStart.getFullYear(), calStart.getMonth(), 1)
    : startOfWeek(calStart);
  drawCalendar();
}
function calGotoWeek(iso) {
  calView = "week";
  localStorage.setItem("tpuhive-cal-view", calView);
  calStart = startOfWeek(new Date(iso));
  drawCalendar();
}

function toggleResPicker() {
  const panel = document.getElementById("respick-panel");
  panel.style.display = panel.style.display === "none" ? "block" : "none";
}
function calToggleResource(uid) {
  if (calSelected.has(uid)) calSelected.delete(uid); else calSelected.add(uid);
  localStorage.setItem("tpuhive-cal", JSON.stringify([...calSelected]));
  drawCalendar();
}
function calSelectHost(hostname, on) {
  for (const resource of calResources) {
    if (resource.hostname !== hostname) continue;
    if (on) calSelected.add(resource.uid); else calSelected.delete(resource.uid);
  }
  localStorage.setItem("tpuhive-cal", JSON.stringify([...calSelected]));
  drawCalendar();
}

async function drawCalendar() {
  const viewButton = document.getElementById("cal-view-btn");
  if (viewButton) viewButton.textContent = calView === "month" ? "week view" : "month view";
  const gridStart = calView === "month" ? startOfWeek(calStart) : calStart;
  const end = new Date(gridStart);
  end.setDate(end.getDate() + (calView === "month" ? 42 : 7));
  document.getElementById("cal-range").textContent = calView === "month"
    ? calStart.toLocaleDateString(undefined, { month: "long", year: "numeric" })
    : calStart.toDateString() + " – " + new Date(end - 1).toDateString();
  try {
    [calResources, calEvents] = await Promise.all([
      api("/resources"),
      api(`/reservations?start=${gridStart.toISOString()}&end=${end.toISOString()}`)]);
  } catch (e) { return toast(e.message, true); }
  if (calSelected === null) {
    calSelected = loadSelected() || new Set(calResources.map(r => r.uid));
  }
  drawResPicker();
  const shown = calEvents.filter(r => calSelected.has(r.resourceId));
  if (calView === "month") return drawMonth(gridStart, shown);

  const days = [...Array(7)].map((_, i) => {
    const d = new Date(calStart); d.setDate(d.getDate() + i); return d; });
  const today = new Date(); today.setHours(0, 0, 0, 0);
  let html = `<div class="tgrid"><div class="corner"></div>` +
    days.map(d => `<div class="dayhead ${+d === +today ? "today" : ""}">
      ${d.toDateString().slice(0, 10)}</div>`).join("");
  // body rows: one label column + 7 day columns, each a positioned stack
  html += `<div style="display:contents">`;
  html += `<div class="hourlabel"><div style="height:${SLOT_PX * 48}px;position:relative">` +
    [...Array(24)].map((_, hour) =>
      `<div style="position:absolute;top:${hour * 2 * SLOT_PX - 7}px;right:4px">
        ${hour ? String(hour).padStart(2, "0") + ":00" : ""}</div>`).join("") +
    `</div></div>`;
  for (let i = 0; i < 7; i++) {
    const day = days[i], dayEnd = new Date(day); dayEnd.setDate(dayEnd.getDate() + 1);
    const events = shown.filter(r =>
      new Date(r.start) < dayEnd && new Date(r.end) > day);
    html += `<div class="daycol" data-day="${i}"
        style="height:${SLOT_PX * 48}px">` +
      [...Array(48)].map(() => `<div class="slot"></div>`).join("") +
      events.map(r => calEventHtml(r, day, dayEnd)).join("") +
      `</div>`;
  }
  html += `</div></div>`;
  const cal = document.getElementById("cal");
  cal.innerHTML = html;
  attachDragHandlers(cal, days);
}

function drawMonth(gridStart, shown) {
  const today = new Date(); today.setHours(0, 0, 0, 0);
  const month = calStart.getMonth();
  let html = `<div class="mgrid">` +
    ["Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun"]
      .map(n => `<div class="dayhead">${n}</div>`).join("");
  for (let i = 0; i < 42; i++) {
    const day = new Date(gridStart); day.setDate(day.getDate() + i);
    const dayEnd = new Date(day); dayEnd.setDate(dayEnd.getDate() + 1);
    const events = shown.filter(r =>
      new Date(r.start) < dayEnd && new Date(r.end) > day && !r.isCancelled);
    const classes = ["mday"];
    if (+day === +today) classes.push("today");
    if (day.getMonth() !== month) classes.push("other-month");
    html += `<div class="${classes.join(" ")}"
        onclick="calGotoWeek('${day.toISOString()}')">
      <div class="mday-num">${day.getDate()}</div>` +
      events.slice(0, 3).map(r => `<span class="mev"
        style="background:hsl(${resourceHue(r.resourceId)},65%,${
          state.user && r.userId === state.user.id ? 70 : 55}%)"
        title="${esc(r.title)} — ${esc(r.resourceId)}"
        onclick="openReservationDetails(${r.id});event.stopPropagation()">
        ${esc(r.title)}</span>`).join("") +
      (events.length > 3
        ? `<span class="muted">+${events.length - 3} more</span>` : "") +
      `</div>`;
  }
  document.getElementById("cal").innerHTML = html + `</div>`;
}

function drawResPicker() {
  document.getElementById("respick-count").textContent =
    `(${calSelected.size}/${calResources.length})`;
  const byHost = {};
  for (const resource of calResources) {
    (byHost[resource.hostname] = byHost[resource.hostname] || []).push(resource);
  }
  document.getElementById("respick-panel").innerHTML =
    Object.keys(byHost).sort().map(host => {
      const chips = byHost[host];
      const allOn = chips.every(r => calSelected.has(r.uid));
      return `<label><input type="checkbox" ${allOn ? "checked" : ""}
          onchange="calSelectHost('${jsArg(host)}', this.checked)"><b>${esc(host)}</b></label>` +
        chips.map(r => `<label style="margin-left:1.1rem">
          <input type="checkbox" ${calSelected.has(r.uid) ? "checked" : ""}
            onchange="calToggleResource('${jsArg(r.uid)}')">
          <span class="legend-dot"
            style="background:hsl(${resourceHue(r.uid)},65%,60%)"></span>
          ${esc(r.uid)}</label>`).join("");
    }).join("") || `<span class="muted">no resources yet</span>`;
}

function calEventHtml(r, day, dayEnd) {
  const start = new Date(Math.max(new Date(r.start), day));
  const end = new Date(Math.min(new Date(r.end), dayEnd));
  const top = ((start - day) / 6e4 / SLOT_MIN) * SLOT_PX;
  const height = Math.max(10, ((end - start) / 6e4 / SLOT_MIN) * SLOT_PX - 2);
  const mine = state.user && r.userId === state.user.id;
  const hue = resourceHue(r.resourceId);
  const style = r.isCancelled ? "" :
    `background:hsl(${hue},65%,${mine ? 70 : 55}%);`;
  return `<span class="ev ${mine ? "mine" : ""} ${r.isCancelled ? "cancelled" : ""}"
    style="top:${top}px;height:${height}px;${style}"
    title="${esc(r.title)} — ${esc(r.resourceId)} (#${r.id})"
    onclick="openReservationDetails(${r.id});event.stopPropagation()">
    ${esc(r.title)}<br><small>${esc(r.resourceId.split(":").slice(-2).join(":"))}</small>
  </span>`;
}

/* drag-to-select (reference: FullCalendar select callback). The mouseup
   listener is document-level and persistent — a per-draw {once} listener
   would be consumed by any unrelated click and disarm dragging. */
function attachDragHandlers(cal, days) {
  const slotOfEvent = (col, ev) => {
    const rect = col.getBoundingClientRect();
    return Math.max(0, Math.min(48, Math.round((ev.clientY - rect.top) / SLOT_PX)));
  };
  cal.querySelectorAll(".daycol").forEach(col => {
    col.addEventListener("mousedown", ev => {
      if (ev.target.closest(".ev") || ev.button !== 0) return;
      calDrag = { day: days[+col.dataset.day], fromSlot: slotOfEvent(col, ev),
                  toSlot: slotOfEvent(col, ev) + 1, col };
      updateDragSel();
      ev.preventDefault();
    });
    col.addEventListener("mousemove", ev => {
      if (!calDrag || calDrag.col !== col) return;
      calDrag.toSlot = Math.max(calDrag.fromSlot + 1, slotOfEvent(col, ev));
      updateDragSel();
    });
  });
}
document.addEventListener("mouseup", () => {
  if (!calDrag) return;
  const { day, fromSlot, toSlot } = calDrag;
  clearDragSel(); calDrag = null;
  const start = new Date(day);
  start.setMinutes(start.getMinutes() + fromSlot * SLOT_MIN);
  const end = new Date(day);
  end.setMinutes(end.getMinutes() + toSlot * SLOT_MIN);
  openReservationDialog(start, end);
});
function updateDragSel() {
  clearDragSel();
  const { col, fromSlot, toSlot } = calDrag;
  const el = document.createElement("span");
  el.className = "ev dragsel";
  el.style.top = fromSlot * SLOT_PX + "px";
  el.style.height = (toSlot - fromSlot) * SLOT_PX + "px";
  col.appendChild(el);
}
function clearDragSel() {
  document.querySelectorAll(".ev.dragsel").forEach(el => el.remove());
}

/* create dialog — one reservation per selected chip (reference creates one
   event per selected GPU) */
function openReservationDialog(start, end) {
  const dialog = document.getElementById("res-dialog");
  if (!start) {
    start = new Date(); start.setMinutes(0, 0, 0); start.setHours(start.getHours() + 1);
    end = new Date(start); end.setHours(end.getHours() + 2);
  }
  const preset = calSelected ? [...calSelected] : [];   // pre-first-draw click
  dialog.innerHTML = `<h3>New reservation</h3>
    <label>Title</label><input id="rd-title" value="training run">
    <label>Description</label><input id="rd-desc" value="">
    <label>Chips <span class="muted">(one reservation per chip)</span></label>
    <div style="max-height:160px;overflow-y:auto">${calResources.map(r => `
      <label class="inline"><input type="checkbox" class="rd-chip"
        value="${esc(r.uid)}" ${preset.includes(r.uid) ? "checked" : ""}>
        ${esc(r.uid)}</label>`).join("")}</div>
    <label>Start</label><input id="rd-start" type="datetime-local"
      value="${toLocalInput(start)}">
    <label>End</label><input id="rd-end" type="datetime-local"
      value="${toLocalInput(end)}">
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="createReservations()">Reserve</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
async function createReservations() {
  const chips = [...document.querySelectorAll(".rd-chip:checked")].map(el => el.value);
  if (!chips.length) return toast("pick at least one chip", true);
  const payload = uid => ({
    title: document.getElementById("rd-title").value,
    description: document.getElementById("rd-desc").value,
    resourceId: uid,
    start: fromLocalInput(document.getElementById("rd-start").value),
    end: fromLocalInput(document.getElementById("rd-end").value) });
  let created = 0;
  for (const uid of chips) {
    try { await api("/reservations", { json: payload(uid) }); created++; }
    catch (e) { toast(`${uid}: ${e.message}`, true); }
  }
  if (created) {
    toast(`created ${created} reservation${created > 1 ? "s" : ""}`);
    document.getElementById("res-dialog").close();
    drawCalendar();
  }
}

/* details/edit dialog (reference: event click -> edit/cancel modal) */
async function openReservationDetails(id) {
  const r = await api("/reservations/" + id);
  const dialog = document.getElementById("res-dialog");
  const editable = isAdmin() || (state.user && r.userId === state.user.id);
  dialog.innerHTML = `<h3>Reservation <span class="muted">#${r.id}</span></h3>
    <p class="muted">${esc(r.resourceId)} · user #${r.userId}
      ${r.isCancelled ? '· <span class="err">cancelled</span>' : ""}<br>
      ${r.dutyCycleAvg != null ?
        `avg duty ${r.dutyCycleAvg}% · avg HBM ${r.hbmUtilAvg}%` : ""}</p>
    <label>Title</label><input id="rd-title" value="${esc(r.title)}"
      ${editable ? "" : "disabled"}>
    <label>Description</label><input id="rd-desc" value="${esc(r.description)}"
      ${editable ? "" : "disabled"}>
    <label>Start</label><input id="rd-start" type="datetime-local"
      value="${toLocalInput(new Date(r.start))}" ${editable ? "" : "disabled"}>
    <label>End</label><input id="rd-end" type="datetime-local"
      value="${toLocalInput(new Date(r.end))}" ${editable ? "" : "disabled"}>
    <div class="row" style="margin-top:1rem">
      ${editable ? `
        <button class="primary" onclick="saveReservation(${r.id})">Save</button>
        <button class="ghost danger" onclick="deleteReservation(${r.id})">Delete</button>` : ""}
      <button class="ghost" onclick="this.closest('dialog').close()">Close</button>
    </div>`;
  dialog.showModal();
}
async function saveReservation(id) {
  try {
    await api("/reservations/" + id, { method: "PUT", json: {
      title: document.getElementById("rd-title").value,
      description: document.getElementById("rd-desc").value,
      start: fromLocalInput(document.getElementById("rd-start").value),
      end: fromLocalInput(document.getElementById("rd-end").value) } });
    document.getElementById("res-dialog").close();
    toast("reservation updated"); drawCalendar();
  } catch (e) { toast(e.message, true); }
}
async function deleteReservation(id) {
  try {
    await api("/reservations/" + id, { method: "DELETE" });
    document.getElementById("res-dialog").close(); drawCalendar();
  } catch (e) { toast(e.message, true); }
}
