"use strict";
/* nodes dashboard: live telemetry + rolling utilization history charts.
   Reference: NodesOverview + WatchBox.vue (setInterval poll, :192,236) +
   LineChart.vue (vue-chartjs). History is a client-side ring buffer per chip
   — the API serves snapshots, the reference charts the same way. */

const NODES_POLL_MS = 3000;
const HISTORY_MAX = 200;                      // ~10 min at 3 s/sample
const chipHistory = {};                       // uid -> {duty:[], hbm:[]}

/* selectable history window for the popout chart (reference WatchBox.vue
   charts a fixed rolling window with time labels, :240); persisted like
   the watch toggles. Sample counts derive from the poll period so the
   option names can never drift from the charted span. */
const CHART_WINDOWS = {
  "2 min": Math.min(HISTORY_MAX, 2 * 60000 / NODES_POLL_MS),
  "5 min": Math.min(HISTORY_MAX, 5 * 60000 / NODES_POLL_MS),
  "10 min": Math.min(HISTORY_MAX, 10 * 60000 / NODES_POLL_MS),
};

let currentChartWindow = null;    // survives even when storage is blocked

function chartWindow() {
  if (currentChartWindow && CHART_WINDOWS[currentChartWindow]) {
    return currentChartWindow;
  }
  try {
    const v = localStorage.getItem("tpuhive-chart-window");
    if (v && CHART_WINDOWS[v]) return v;
  } catch (e) {}
  return "10 min";
}

function setChartWindow(name, uid) {
  currentChartWindow = name;
  try { localStorage.setItem("tpuhive-chart-window", name); } catch (e) {}
  drawChipChart(uid);
}

function recordChipSample(uid, duty, hbmPct) {
  const h = chipHistory[uid] || (chipHistory[uid] = { duty: [], hbm: [] });
  h.duty.push(duty ?? 0); h.hbm.push(hbmPct ?? 0);
  if (h.duty.length > HISTORY_MAX) { h.duty.shift(); h.hbm.shift(); }
}

function sparkline(values, cls) {
  const w = 100, h = 36;
  if (!values.length) return `<svg class="spark ${cls}" viewBox="0 0 ${w} ${h}"></svg>`;
  const pts = values.map((v, i) => {
    const x = values.length === 1 ? w : (i / (values.length - 1)) * w;
    const y = h - 2 - (Math.min(100, Math.max(0, v)) / 100) * (h - 4);
    return `${x.toFixed(1)},${y.toFixed(1)}`;
  });
  const fill = `0,${h} ${pts.join(" ")} ${w},${h}`;
  return `<svg class="spark ${cls}" viewBox="0 0 ${w} ${h}" preserveAspectRatio="none">
    <polygon class="fill" points="${fill}"></polygon>
    <polyline points="${pts.join(" ")}"></polyline></svg>`;
}

/* user-configurable watches (reference WatchBox.vue:192-236: each watch box
   picks its metric): which panels every chip card shows, persisted */
function loadWatches() {
  try {
    const saved = JSON.parse(localStorage.getItem("tpuhive-watches") || "null");
    if (saved && typeof saved === "object") {
      return { hbm: !!saved.hbm, duty: !!saved.duty, procs: !!saved.procs };
    }
  } catch (e) {}
  return { hbm: true, duty: true, procs: true };
}
let nodesWatch = loadWatches();
const WATCH_LABELS = { hbm: "HBM", duty: "duty cycle", procs: "processes" };

function toggleWatch(name, on) {
  nodesWatch[name] = on;
  localStorage.setItem("tpuhive-watches", JSON.stringify(nodesWatch));
}

function renderNodes(main) {
  main.innerHTML = `<div id="svc-health"></div>
    <div id="alert-strip"></div>
    <div id="serving-strip"></div>
    <div id="requests-strip"></div>
    <div id="tenants-strip"></div>
    <div class="card"><div class="row">
      <h3 style="margin:0">Watches</h3>
      ${["hbm", "duty", "procs"].map(name => `<label class="inline">
        <input type="checkbox" ${nodesWatch[name] ? "checked" : ""}
          onchange="toggleWatch('${name}', this.checked)">
        ${WATCH_LABELS[name]}
      </label>`).join("")}
    </div></div>
    <div id="nodes"></div><dialog id="chip-dialog"></dialog>`;
  const refresh = async () => {
    try {
      if (isAdmin()) {
        refreshAlerts(); refreshRecentRequests(); refreshTenants();
        await refreshHistory();       // sparkline data for the strips below
        refreshServiceHealth();
      }
      refreshServing();
      const infra = await api("/nodes/metrics");
      for (const node of Object.values(infra)) {
        for (const [uid, chip] of Object.entries(node.TPU || {})) {
          recordChipSample(uid, chip.duty_cycle_pct, chip.hbm_util_pct);
        }
      }
      const el = document.getElementById("nodes");
      if (!el) return;                        // view switched mid-flight
      el.innerHTML =
        Object.keys(infra).sort().map(host => nodeCard(host, infra[host])).join("")
        || `<p class="muted">No telemetry yet — are hosts configured?</p>`;
      const open = document.querySelector("#chip-dialog[open]");
      if (open && open.dataset.uid) drawChipChart(open.dataset.uid);
    } catch (e) { toast(e.message, true); }
  };
  refresh();
  state.timers.push(setInterval(refresh, NODES_POLL_MS));
}

/* server-side metrics history (admin, GET /admin/history): downsampled
   min/mean/max windows from the in-process ring TSDB — unlike the
   client-side chipHistory ring above, these survive page reloads and
   cover the whole retention window (docs/OBSERVABILITY.md "History,
   SLOs & flight recorder") */
const HISTORY_SERIES = [
  "tpuhive_generate_queue_depth",
  "tpuhive_generate_slots_busy",
  "tpuhive_process_resident_memory_bytes",
];
let metricsHistory = {};                      // series -> [window points]

async function refreshHistory() {
  try {
    const doc = await api("/admin/history?series=" +
                          encodeURIComponent(HISTORY_SERIES.join(",")));
    metricsHistory = doc.series || {};
  } catch (e) { metricsHistory = {}; }  // [history] disabled (404) or down
}

/* one series from the store as a sparkline, peak-normalized (sparkline()
   clamps to 0-100); empty until two windows exist so strips never show a
   meaningless single-point line */
function historySpark(name, cls, title) {
  const points = metricsHistory[name] || [];
  if (points.length < 2) return "";
  const peak = Math.max(...points.map(p => p.max), 1e-9);
  return `<span class="spark-wrap" title="${esc(title)} · peak ${peak}">
    ${sparkline(points.map(p => (100 * p.mean) / peak), cls)}</span>`;
}

/* daemon service health strip (admin): tick p50/p95/max + liveness per
   service, plus entry points to the observability layer (Prometheus
   exposition + recent spans) */
function svcBadge(svc) {
  const lat = svc.tickP50Ms != null
    ? "· " + svc.tickP50Ms + "/" + (svc.tickP95Ms ?? "?") + "ms p50/p95"
    : "";
  const over = svc.tickOverruns ? " · " + svc.tickOverruns + " overruns" : "";
  const detail = "every " + svc.intervalS + "s · " + svc.ticksCompleted +
    " ticks" + over +
    (svc.tickMaxMs != null ? " · max " + svc.tickMaxMs + "ms" : "");
  return `<span class="badge ${svc.alive ? "on" : "unsynchronized"}"
    title="${esc(detail)}">
    ${esc(svc.name)} ${svc.alive ? "✓" : "DOWN"} ${lat}</span>`;
}

async function refreshServiceHealth() {
  const el = document.getElementById("svc-health");
  if (!el) return;
  let services;
  try { services = await api("/admin/services"); }
  catch (e) {
    // a health display must never keep asserting "alive" when the probe
    // itself fails — mark the whole strip unknown instead
    el.innerHTML = `<div class="card"><div class="row">
      <h3 style="margin:0">Services</h3>
      <span class="badge unsynchronized">health unavailable: ${esc(e.message)}</span>
    </div></div>`;
    return;
  }
  if (!services.length) { el.innerHTML = ""; return; }
  el.innerHTML = `<div class="card"><div class="row">
    <h3 style="margin:0">Services</h3>
    ${services.map(svcBadge).join("")}
    ${historySpark("tpuhive_process_resident_memory_bytes", "hbm",
                   "manager RSS over the history window")}
    <button class="ghost" onclick="openTracesDialog()">traces</button>
    <button class="ghost" onclick="captureProfile()"
      title="capture a jax.profiler trace to the artifact dir (404 while [profiling] is disabled)">profile</button>
    <button class="ghost" onclick="showMemoryProfile()"
      title="live per-device HBM snapshot from the XLA memory profiler">HBM</button>
    <a class="ghost" href="/api/metrics" target="_blank"
       title="Prometheus text exposition">metrics</a>
  </div></div>`;
}

/* on-demand device profiling (docs/OBSERVABILITY.md "Request tracing &
   profiling"): POST a bounded trace capture / toast the live-HBM summary;
   404 (profiling disabled) and 409 (capture in flight) surface as toasts */
async function captureProfile() {
  try {
    const doc = await api("/admin/profile", { json: {} });
    toast(`profile captured: ${doc.files.length} files · ` +
          `${(doc.bytes / 1024).toFixed(0)} KiB → ${doc.artifactDir}`);
  } catch (e) { toast(e.message, true); }
}

async function showMemoryProfile() {
  try {
    const doc = await api("/admin/profile/memory");
    const per = (doc.devices || []).map(d =>
      d.device + " " + (d.liveBytes / 1048576).toFixed(1) + " MiB");
    toast(per.length ? "live HBM: " + per.join(" · ")
                     : "no live device buffers");
  } catch (e) { toast(e.message, true); }
}

/* alerts strip (admin): firing/pending rules from the in-process alert
   engine (GET /admin/alerts), shown next to the service-health strip, plus
   entry points to the health probes an orchestrator would watch */
function isActiveAlert(rule) {
  return rule.status === "firing" || rule.status === "pending";
}

function alertBadge(rule) {
  const detail = (rule.description || "") + " · " + rule.severity +
    (rule.lastValue != null ? " · value " + rule.lastValue : "") +
    (rule.firedCount ? " · fired " + rule.firedCount + "×" : "");
  const mark = rule.status === "firing" ? "⚠" : "…";
  return `<span class="badge unsynchronized" title="${esc(detail)}">
    ${mark} ${esc(rule.name)} ${esc(rule.status)}</span>`;
}

async function refreshAlerts() {
  const el = document.getElementById("alert-strip");
  if (!el) return;
  let doc;
  try { doc = await api("/admin/alerts"); }
  catch (e) {
    // like the service strip: never pretend "quiet" when the alert source
    // itself is unreachable
    el.innerHTML = `<div class="card"><div class="row">
      <h3 style="margin:0">Alerts</h3>
      <span class="badge unsynchronized">alerts unavailable: ${esc(e.message)}</span>
    </div></div>`;
    return;
  }
  const rules = doc.rules || [];
  const active = rules.filter(isActiveAlert);
  el.innerHTML = `<div class="card"><div class="row">
    <h3 style="margin:0">Alerts</h3>
    ${active.length ? active.map(alertBadge).join("")
      : '<span class="badge on">all ' + rules.length + ' rules quiet</span>'}
    <span style="flex:1"></span>
    <a class="ghost" href="/api/healthz" target="_blank"
       title="liveness probe">healthz</a>
    <a class="ghost" href="/api/readyz" target="_blank"
       title="readiness probe (503 + reasons when degraded)">readyz</a>
  </div></div>`;
}

/* serving strip: continuous-batching gateway SLOs (GET /generate/stats) —
   queue depth, slot occupancy, TTFT/inter-token percentiles, following the
   alerts-strip pattern. Hidden quietly when serving is disabled (the stats
   endpoint 503s with enabled=false). */
function servingBadge(label, value, hot) {
  return `<span class="badge ${hot ? "unsynchronized" : "on"}">
    ${esc(label)} ${esc(value)}</span>`;
}

async function refreshServing() {
  const el = document.getElementById("serving-strip");
  if (!el) return;
  let stats;
  try { stats = await api("/generate/stats"); }
  catch (e) { el.innerHTML = ""; return; }   // disabled (503) or unreachable
  const ms = v => v == null ? "–" : v.toFixed(1) + "ms";
  el.innerHTML = `<div class="card"><div class="row">
    <h3 style="margin:0">Serving</h3>
    ${!stats.draining ? "" :
      servingBadge("draining", "admission closed", true)}
    ${servingBadge("queue", stats.queueDepth + "/" + stats.queueCapacity,
                   stats.queueDepth >= stats.queueCapacity)}
    ${historySpark("tpuhive_generate_queue_depth", "",
                   "queue depth over the history window")}
    ${servingBadge("slots", stats.slotsBusy + "/" + stats.slots,
                   stats.slotsBusy >= stats.slots && stats.queueDepth > 0)}
    ${historySpark("tpuhive_generate_slots_busy", "",
                   "busy slots over the history window")}
    ${stats.numDevices <= 1 ? "" :
      servingBadge("mesh " + stats.meshShape,
                   stats.numDevices + " devices", false)}
    ${stats.kvPagesTotal == null ? "" :
      servingBadge("KV pages · " + stats.pagedKernel,
                   stats.kvPagesFree + "/" + stats.kvPagesTotal,
                   stats.kvPagesFree === 0)}
    ${stats.kvQuant !== "on" ? "" :
      servingBadge("int8 KV",
                   stats.kvBytesPerToken + " B/token", false)}
    ${stats.prefixCache !== "on" ? "" :
      servingBadge("prefix cache",
                   (stats.prefixHitRate == null ? "–" :
                    (100 * stats.prefixHitRate).toFixed(0) + "% hit") +
                   " · " + stats.cachedPages + " pg", false)}
    ${stats.hostPagesResident == null ? "" :
      servingBadge("host tier",
                   stats.hostPagesResident + " pg · " +
                   (stats.hostHitRate == null ? "–" :
                    (100 * stats.hostHitRate).toFixed(0) + "% hit"), false)}
    ${stats.speculative !== "on" ? "" :
      servingBadge("spec ×" + stats.specTokens,
                   (stats.specAcceptanceRate == null ? "–" :
                    (100 * stats.specAcceptanceRate).toFixed(0) +
                    "% accept"), false)}
    ${servingBadge("TTFT p50/p95",
                   ms(stats.ttftP50Ms) + " / " + ms(stats.ttftP95Ms), false)}
    ${servingBadge("inter-token p50",
                   ms(stats.intertokenP50Ms), false)}
    <span class="muted">${stats.tokensEmitted} tokens ·
      ${stats.requestsCompleted} requests</span>
    <span style="flex:1"></span>
    <button class="ghost" onclick="toggleDrain(${stats.draining})"
      title="admin: drain stops admission (503 + Retry-After) while
             in-flight requests finish; resume reopens it">
      ${stats.draining ? "resume" : "drain"}</button>
    <button class="ghost" onclick="probeGenerate()"
      title="stream a tiny generation through POST /generate">probe</button>
    ${!isAdmin() ? "" : `<button class="ghost" onclick="openFlightRecorder()"
      title="per-tick engine black box + crash dumps from fatal faults
             (404 while flight_recorder is disabled)">flight rec</button>`}
  </div></div>`;
}

/* flight recorder drilldown (admin; docs/OBSERVABILITY.md "History, SLOs
   & flight recorder"): the live per-tick ring the engine stamps, plus the
   crash dumps the supervisor wrote on fatal classifications — the
   post-mortem view that outlives the engine itself */
async function openFlightRecorder() {
  let ring, dumps = [];
  try { ring = await api("/admin/flightrec?limit=40"); }
  catch (e) { return toast(e.message, true); }   // 404 = recorder off
  try { dumps = (await api("/admin/flightrec/dumps")).dumps || []; }
  catch (e) {}
  const dialog = document.getElementById("chip-dialog");
  if (!dialog) return;
  delete dialog.dataset.uid;
  dialog.innerHTML = `<h3 style="margin-top:0">Flight recorder</h3>
    <p class="muted">${ring.engineUp
      ? ring.recorded + " ticks recorded · ring capacity " + ring.capacity
      : "engine down — live ring unavailable; crash dumps below"}</p>
    ${(ring.ticks || []).length ? `<table>
      <tr><th>tick</th><th>ms</th><th>admit</th><th>chunks</th><th>decode</th>
        <th>busy</th><th>queue</th><th>pages</th><th>compiles</th><th>faults</th></tr>
      ${ring.ticks.slice().reverse().map(t => `<tr><td>${t.tick}</td>
        <td>${(1000 * t.durationS).toFixed(2)}</td><td>${t.admitted}</td>
        <td>${t.prefillChunks}</td><td>${t.decodeSlots}</td>
        <td>${t.slotsBusy}</td><td>${t.queueDepth}</td><td>${t.pagesFree}</td>
        <td>${t.compiles}</td>
        <td>${t.faults ? "⚠ " + t.faults : 0}</td></tr>`).join("")}
    </table>` : ""}
    <h4 style="margin-bottom:.3rem">Crash dumps</h4>
    ${dumps.length ? `<table>
      <tr><th>file</th><th>reason</th><th>ticks</th><th>in flight</th></tr>
      ${dumps.map(d => `<tr><td class="kv">${esc(d.file)}</td>
        <td>${esc(d.reason || "")}</td><td>${d.ticks}</td>
        <td>${d.inFlight}</td></tr>`).join("")}</table>`
      : '<p class="muted">none — no fatal engine faults recorded</p>'}
    <div class="row" style="margin-top:.8rem">
      <button class="ghost" onclick="this.closest('dialog').close()">Close</button>
    </div>`;
  dialog.showModal();
}

/* graceful drain / resume (admin; docs/ROBUSTNESS.md "Serving data
   plane"): drain closes admission with an honest Retry-After while
   in-flight requests finish, resume reopens it */
async function toggleDrain(draining) {
  const action = draining ? "resume" : "drain";
  try {
    const doc = await api("/admin/generate/" + action, { json: {} });
    toast(action + ": " + doc.inFlight + " request(s) in flight");
    refreshServing();
  } catch (e) { toast(e.message, true); }
}

/* recent-requests strip (admin): the request-scoped view behind the serving
   strip's aggregates — last ~10 generate requests from the ledger
   (GET /admin/requests) as queue/prefill/decode phase bars + an outcome
   badge, so "TTFT regressed" decomposes into WHICH request and WHICH phase
   (docs/OBSERVABILITY.md "Request tracing & profiling") */
function requestPhaseBar(req) {
  const total = Math.max(req.totalMs || 0, 0.001);
  const seg = (ms, cls, label) => (ms == null || ms <= 0) ? "" :
    `<i class="${cls}" title="${label} ${ms.toFixed(1)}ms"
        style="width:${Math.min(100, 100 * ms / total).toFixed(1)}%"></i>`;
  return `<span class="phase-bar" title="queue ${req.queueMs ?? "–"} /
      prefill ${req.prefillMs ?? "–"} / decode ${req.decodeMs ?? "–"} ms">
    ${seg(req.queueMs, "queue", "queue")}${seg(req.prefillMs, "prefill", "prefill")}${seg(req.decodeMs, "decode", "decode")}</span>`;
}

function requestBadge(req) {
  const ok = req.outcome === "completed";
  const detail = req.requestId + " · " + req.tokens + " tokens · queue " +
    (req.queueMs ?? "–") + "ms · prefill " + (req.prefillMs ?? "–") +
    "ms (bucket " + (req.prefillBucket ?? "–") + ", compile " +
    (req.prefillCompile ?? "–") + ") · TTFT " + (req.ttftMs ?? "–") +
    "ms · decode " + (req.decodeMs ?? "–") + "ms · slot " +
    (req.slot ?? "–") + " · pages " + (req.kvPages ?? "–");
  return `<span class="badge ${ok ? "on" : "unsynchronized"}"
      title="${esc(detail)}">
    ${requestPhaseBar(req)} ${esc(req.outcome || "running")} ·
    ${req.tokens}tok · ${req.ttftMs != null ? req.ttftMs.toFixed(0) + "ms" : "–"}</span>`;
}

async function refreshRecentRequests() {
  const el = document.getElementById("requests-strip");
  if (!el) return;
  let doc;
  try { doc = await api("/admin/requests?limit=10"); }
  catch (e) { el.innerHTML = ""; return; }   // serving quiet or unreachable
  const reqs = doc.requests || [];
  if (!reqs.length) { el.innerHTML = ""; return; }
  el.innerHTML = `<div class="card"><div class="row">
    <h3 style="margin:0">Requests</h3>
    ${reqs.map(requestBadge).join("")}
    <span class="muted">${doc.recorded} recorded · ring ${doc.capacity}</span>
  </div></div>`;
}

function tenantBar(tenant, maxShare) {
  const pct = Math.round(tenant.share * 100);
  const width = maxShare > 0 ? Math.round(tenant.share / maxShare * 100) : 0;
  const detail = tenant.tenant + " · " + tenant.deviceSeconds.toFixed(1) +
    " device-s · " + (tenant.kvByteSeconds / 1e9).toFixed(2) +
    " GB·s KV · queue " + tenant.queueSeconds.toFixed(1) + "s" +
    (tenant.capacityShare != null
      ? " · " + Math.round(tenant.capacityShare * 100) + "% of capacity" : "");
  return `<span class="badge" title="${esc(detail)}">
    ${esc(tenant.tenant)} ${pct}%
    <span style="display:inline-block;height:6px;border-radius:3px;
      background:var(--accent,#4a9);vertical-align:middle;
      width:${Math.max(width, 2) * 0.6}px"></span></span>`;
}

/* top-tenants strip from the accounting plane (GET /admin/usage) — hidden
   while [accounting] is disabled (404) or nothing is attributed yet */
async function refreshTenants() {
  const el = document.getElementById("tenants-strip");
  if (!el) return;
  let doc;
  try { doc = await api("/admin/usage"); }
  catch (e) { el.innerHTML = ""; return; }   // accounting disabled or unreachable
  const tenants = (doc.tenants || []).filter(t => t.deviceSeconds > 0);
  if (!tenants.length) { el.innerHTML = ""; return; }
  const maxShare = tenants[0].share;
  el.innerHTML = `<div class="card"><div class="row">
    <h3 style="margin:0">Tenants</h3>
    ${tenants.map(t => tenantBar(t, maxShare)).join("")}
    <span class="muted">device-second share ·
      ${Math.round(doc.windowS / 60)}m window</span>
  </div></div>`;
}

/* fire one small generation through the streaming endpoint and toast the
   result — raw fetch (not api()): the response is chunked NDJSON, one JSON
   object per line, which the JSON helper cannot parse */
async function probeGenerate() {
  try {
    const resp = await fetch(API + "/generate", {
      method: "POST",
      headers: { "Content-Type": "application/json",
                 Authorization: "Bearer " + state.access },
      body: JSON.stringify({ promptTokens: [1, 2, 3, 4],
                             maxNewTokens: 8, temperature: 0 }) });
    if (resp.status === 429) {
      return toast("serving saturated — retry after " +
                   (resp.headers.get("Retry-After") || "?") + "s", true);
    }
    if (!resp.ok) {
      const body = await resp.json().catch(() => ({}));
      return toast(body.msg || resp.statusText, true);
    }
    const lines = (await resp.text()).trim().split("\n");
    const last = JSON.parse(lines[lines.length - 1]);
    if (last.error) return toast("generate: " + last.error, true);
    toast(`generated ${last.tokens.length} tokens · TTFT ${last.ttftMs}ms`);
    refreshServing();
  } catch (e) { toast(e.message, true); }
}

/* recent-span dump from the ring-buffer tracer (GET /admin/traces) */
async function openTracesDialog() {
  let doc;
  try { doc = await api("/admin/traces?limit=100"); }
  catch (e) { return toast(e.message, true); }
  const dialog = document.getElementById("chip-dialog");
  if (!dialog) return;
  delete dialog.dataset.uid;
  const spans = (doc.spans || []).slice().reverse();   // newest first
  dialog.innerHTML = `<h3 style="margin-top:0">Recent spans</h3>
    <p class="muted">${doc.recorded} recorded · ring capacity ${doc.capacity}</p>
    <table><tr><th>seq</th><th>kind</th><th>span</th><th>ms</th><th>status</th></tr>
      ${spans.map(sp => `<tr><td>${sp.seq}</td><td>${esc(sp.kind)}</td>
        <td class="kv" title="${esc(JSON.stringify(sp.attrs))}">
          ${sp.parentId ? "↳ " : ""}${esc(sp.name)}</td>
        <td>${sp.durationMs != null ? sp.durationMs : "–"}</td>
        <td>${sp.status === "ok" ? "✓" : "⚠ " + esc(sp.status)}</td></tr>`).join("")}
    </table>
    <div class="row" style="margin-top:.8rem">
      <button class="ghost" onclick="this.closest('dialog').close()">Close</button>
    </div>`;
  dialog.showModal();
}

/* admin drain/resume for one host (docs/ROBUSTNESS.md "Host membership &
   leases"): drain = no new work there, running jobs stopped gracefully,
   reservations kept; resume puts it straight back to work */
async function toggleHostDrain(host, draining) {
  const action = draining ? "resume" : "drain";
  try {
    const doc = await api("/admin/hosts/" + encodeURIComponent(host) + "/" + action, { json: {} });
    toast(host + " " + action + "ed: lease " + (doc.lease.effective || doc.lease.state));
  } catch (e) { toast(e.message, true); }
}

function leaseBadge(lease) {
  if (!lease.effective || lease.effective === "live") return "";
  const agent = lease.source === "agent";
  const detail = agent
    ? "membership lease from the host agent (POST /agent/report): seq " +
      (lease.seq ?? "–") + ", last report " +
      (lease.age_s != null ? lease.age_s + "s ago" : "never") +
      " (docs/ROBUSTNESS.md 'Host membership & leases')"
    : "admin drain: no new work lands here until resumed";
  return `<div class="badge unsynchronized" style="margin-top:.3rem"
      title="${esc(detail)}">⏻ lease: ${esc(lease.effective)}</div>`;
}

function nodeCard(host, node) {
  const cpu = Object.values(node.CPU || {})[0];
  const chips = Object.entries(node.TPU || {});
  const warnings = node.WARNINGS || [];
  const health = node.HEALTH || {};
  const lease = node.LEASE || {};
  const unhealthy = health.state === "degraded" || health.state === "unreachable";
  const staleFor = health.staleness_s != null
    ? Math.round(health.staleness_s) + "s ago" : "never";
  const healthBadge = unhealthy
    ? `<div class="badge unsynchronized" style="margin-top:.3rem"
        title="telemetry below is last-known-good, not live (docs/ROBUSTNESS.md)">⚠ ${esc(health.state)}: last seen ${esc(staleFor)}</div>`
    : "";
  return `<div class="card">
    <div class="row">
      <h3 style="margin:.1rem 0;cursor:pointer" title="node details"
          onclick="openHostDialog('${jsArg(host)}')">${esc(host)}</h3>
      <span class="muted">${cpu ? `CPU ${cpu.util_pct ?? "?"}% ·
        RAM ${cpu.mem_used_mib ?? "?"}/${cpu.mem_total_mib ?? "?"} MiB` : "no CPU data"}</span>
      ${!isAdmin() ? "" : `<button class="ghost" style="margin-left:auto"
        title="${lease.draining ? "resume: the host takes work again"
          : "drain: no new work, running jobs stopped gracefully"}"
        onclick="toggleHostDrain('${jsArg(host)}', ${!!lease.draining})">
        ${lease.draining ? "Resume" : "Drain"}</button>`}
    </div>
    ${healthBadge}
    ${leaseBadge(lease)}
    ${warnings.map(w => `<div class="badge unsynchronized" style="margin-top:.3rem"
      title="${esc(w.message || "")}">⚠ ${esc(w.key || "warning")}: ${esc(w.message || "")}</div>`).join("")}
    <div class="grid" style="margin-top:.6rem">${chips.map(([uid, c]) => chipCard(uid, c, host)).join("")
      || '<span class="muted">no TPU chips visible</span>'}</div>
  </div>`;
}

/* single-node drilldown: GET /nodes/<host>/metrics + /nodes/<host>/cpu/metrics */
async function openHostDialog(host) {
  const dialog = document.getElementById("chip-dialog");
  delete dialog.dataset.uid;
  let node = {}, cpuMap = {};
  try {
    [node, cpuMap] = await Promise.all([
      api(`/nodes/${encodeURIComponent(host)}/metrics`),
      api(`/nodes/${encodeURIComponent(host)}/cpu/metrics`)]);
  } catch (e) { return toast(e.message, true); }
  const cpu = Object.values(cpuMap || {})[0] || {};
  const chips = Object.entries(node.TPU || {});
  dialog.innerHTML = `<h3 style="margin-top:0">${esc(host)}</h3>
    <p class="muted">CPU ${cpu.util_pct ?? "?"}% ·
      RAM ${cpu.mem_used_mib ?? "?"}/${cpu.mem_total_mib ?? "?"} MiB</p>
    <table><tr><th>chip</th><th>HBM MiB</th><th>duty %</th><th>procs</th></tr>
      ${chips.map(([uid, c]) => `<tr><td>${esc(uid)}</td>
        <td>${c.hbm_used_mib ?? "?"} / ${c.hbm_total_mib ?? "?"}</td>
        <td>${c.duty_cycle_pct ?? "–"}</td>
        <td>${(c.processes || []).length}</td></tr>`).join("")}
    </table>
    <div class="row" style="margin-top:.8rem">
      <button class="ghost" onclick="this.closest('dialog').close()">Close</button>
    </div>`;
  dialog.showModal();
}

function chipCard(uid, chip, host) {
  const hbmPct = chip.hbm_util_pct, duty = chip.duty_cycle_pct;
  const procs = (chip.processes || []);
  const hist = chipHistory[uid] || { duty: [], hbm: [] };
  return `<div class="chip-card" onclick="openChipDialog('${jsArg(uid)}','${jsArg(host)}')"
               title="click for history">
    <b>${esc(chip.name || uid)}</b> <span class="muted">${esc(uid)}</span>
    ${nodesWatch.hbm ? `
      <div class="muted">HBM ${chip.hbm_used_mib ?? "?"} / ${chip.hbm_total_mib ?? "?"} MiB</div>
      <div class="bar ${hbmPct > 85 ? "hot" : ""}"><i style="width:${hbmPct || 0}%"></i></div>
      ${sparkline(hist.hbm, "hbm")}` : ""}
    ${nodesWatch.duty ? `
      <div class="muted">duty ${duty != null ? duty + "%" : "–"}</div>
      <div class="bar"><i style="width:${duty || 0}%"></i></div>
      ${sparkline(hist.duty, "")}` : ""}
    ${nodesWatch.procs ? procs.map(p => `<div class="muted" title="${esc(p.command)}">
        ${p.pid} <b>${esc(p.user)}</b> ${esc((p.command || "").slice(0, 28))}</div>`).join("")
      || '<div class="ok">idle</div>' : ""}
  </div>`;
}

/* large history chart dialog (reference WatchBox chart popout); also pulls
   the chip inventory + live process list for this node and the persisted
   Resource row so acceleratorType / slice metadata show up */
function openChipDialog(uid, host) {
  const dialog = document.getElementById("chip-dialog");
  dialog.dataset.uid = uid;
  dialog.innerHTML = `<h3 style="margin-top:0">${esc(uid)}</h3>
    <p class="muted" id="chip-meta">loading…</p>
    <p class="muted">
      <span class="legend-dot" style="background:var(--accent)"></span>duty cycle %
      <span class="legend-dot" style="background:var(--ok);margin-left:1rem"></span>HBM %
      <label class="inline" style="margin-left:1rem">window
        <select id="chip-window" onchange="setChartWindow(this.value, '${jsArg(uid)}')">
          ${Object.keys(CHART_WINDOWS).map(name =>
            `<option ${name === chartWindow() ? "selected" : ""}>${name}</option>`).join("")}
        </select></label>
    </p>
    <svg class="chart-lg" id="chip-chart" viewBox="0 0 600 180"
         preserveAspectRatio="none"></svg>
    <div id="chip-procs"></div>
    <div class="row" style="margin-top:.8rem">
      <button class="ghost" onclick="this.closest('dialog').close()">Close</button>
    </div>`;
  dialog.showModal();
  drawChipChart(uid);
  Promise.all([
    api("/resources/" + encodeURIComponent(uid)).catch(() => null),
    api(`/nodes/${encodeURIComponent(host)}/tpu/info`).catch(() => ({})),
    api(`/nodes/${encodeURIComponent(host)}/tpu/processes`).catch(() => ({})),
  ]).then(([resource, info, processes]) => {
    const meta = document.getElementById("chip-meta");
    if (meta) {
      const inv = (Array.isArray(info) ? info : [])
        .find(c => c.uid === uid || c.name === uid) || {};
      meta.textContent = [
        resource && resource.acceleratorType,
        resource && resource.sliceName && `slice ${resource.sliceName}`,
        resource && resource.topology &&
          `${resource.topology} (${resource.numChips} chips)`,
        inv.name,
      ].filter(Boolean).join(" · ") || "no inventory metadata";
    }
    const procsEl = document.getElementById("chip-procs");
    if (procsEl) {
      const procs = (processes || {})[uid] || [];
      procsEl.innerHTML = procs.length ? `<table style="margin-top:.6rem">
        <tr><th>pid</th><th>user</th><th>command</th></tr>
        ${procs.map(p => `<tr><td>${p.pid}</td><td>${esc(p.user)}</td>
          <td class="kv">${esc((p.command || "").slice(0, 60))}</td></tr>`).join("")}
        </table>` : `<p class="ok" style="margin:.5rem 0 0">idle</p>`;
    }
  });
}

function drawChipChart(uid) {
  const svg = document.getElementById("chip-chart");
  if (!svg) return;
  const h = chipHistory[uid] || { duty: [], hbm: [] };
  const w = 600, ht = 180;
  /* fixed timescale: the x axis always spans the selected window ("now"
     at the right edge); with fewer samples than the window holds, the
     trace starts partway in rather than stretching (reference
     WatchBox.vue:240 labels its chart the same seconds-ago way) */
  const windowSamples = CHART_WINDOWS[chartWindow()];
  const line = (allValues, color) => {
    const values = allValues.slice(-windowSamples);
    if (!values.length) return "";
    const pts = values.map((v, i) => {
      /* slot+1 of windowSamples: the newest sample sits at the right
         edge and the LEFT edge is exactly windowSamples polls ago, so
         the seconds-ago labels below are exact */
      const slot = windowSamples - values.length + i;
      const x = ((slot + 1) / windowSamples) * w;
      const y = ht - 4 - (Math.min(100, Math.max(0, v)) / 100) * (ht - 8);
      return `${x.toFixed(1)},${y.toFixed(1)}`;
    }).join(" ");
    return `<polyline points="${pts}" fill="none" stroke="${color}" stroke-width="1.5"/>`;
  };
  const gridlines = [25, 50, 75].map(pct => {
    const y = ht - 4 - (pct / 100) * (ht - 8);
    return `<line x1="0" y1="${y}" x2="${w}" y2="${y}" stroke="#2e3943"
      stroke-dasharray="4 5"/><text x="4" y="${y - 3}" fill="#8b98a5"
      font-size="9">${pct}%</text>`;
  }).join("");
  const timeLabels = [0, 0.5].map(frac => {
    const secsAgo = Math.round((1 - frac) * windowSamples * NODES_POLL_MS / 1000);
    return `<text x="${(frac * w + 4).toFixed(0)}" y="${ht - 6}" fill="#8b98a5"
      font-size="9">-${secsAgo}s</text>`;
  }).join("") + `<text x="${w - 26}" y="${ht - 6}" fill="#8b98a5" font-size="9">now</text>`;
  svg.innerHTML = gridlines + timeLabels +
    line(h.duty, "var(--accent)") + line(h.hbm, "var(--ok)");
}
