"use strict";
/* jobs: list + schedule + queue + per-task editor.
   Reference: JobsOverview.vue + JobDetailsView.vue + job_tasks/TaskCreate.vue
   (861 LoC: per-process distributed parameter auto-fill, env/param segment
   rows, GPU->CUDA_VISIBLE_DEVICES selection). The template auto-fill engine
   lives server-side here (core/templates.py, POST /jobs/{id}/tasks_from_template);
   the editor exposes it plus raw per-task segment editing. */

let jobsSelectedId = null;                    // open details drawer
let jobsHostnames = [];

function renderJobs(main) {
  main.innerHTML = `<div class="card">
    <div class="row">
      <h3 style="margin:0">Jobs</h3><span style="flex:1"></span>
      <button class="primary" onclick="openJobDialog()">New job</button>
    </div>
    <div id="job-list" style="margin-top:.8rem"></div>
  </div>
  <div id="job-details"></div>
  <dialog id="job-dialog"></dialog>`;
  api("/nodes/hostnames").then(h => jobsHostnames = h).catch(() => {});
  const refresh = () => loadJobs().catch(e => toast(e.message, true));
  refresh();
  state.timers.push(setInterval(refresh, 5000));
}

async function loadJobs() {
  const jobs = await api("/jobs");
  const el = document.getElementById("job-list");
  if (!el) return;
  el.innerHTML = jobs.length ? `
    <table><tr><th>id</th><th>name</th><th>status</th><th>queue</th>
      <th>schedule</th><th>tasks</th><th></th></tr>
    ${jobs.map(j => `<tr>
      <td>${j.id}</td><td>${esc(j.name)}</td>
      <td><span class="badge ${esc(j.status)}">${esc(j.status)}</span></td>
      <td>${j.isQueued ? '<span class="badge on">queued</span>' : ""}</td>
      <td class="muted">${j.startAt ? "▶ " + fmtDt(j.startAt) : ""}
          ${j.stopAt ? "■ " + fmtDt(j.stopAt) : ""}</td>
      <td>${(j.tasks || []).length}</td>
      <td class="row">
        <button class="ghost small" onclick="openJobDetails(${j.id})">details</button>
        <button class="ghost small" onclick="jobAction(${j.id},'execute')">run</button>
        <button class="ghost small" onclick="jobStop(${j.id})">stop</button>
        <button class="ghost small" onclick="jobQueue(${j.id}, ${j.isQueued})">
          ${j.isQueued ? "dequeue" : "enqueue"}</button>
        <button class="ghost small danger" onclick="deleteJob(${j.id})">✕</button>
      </td></tr>`).join("")}</table>` :
    `<p class="muted">No jobs yet.</p>`;
  if (jobsSelectedId !== null) {
    const open = jobs.find(j => j.id === jobsSelectedId);
    if (open) drawJobDetails(); else { jobsSelectedId = null; jobDetailsEl().innerHTML = ""; }
  }
}
const jobDetailsEl = () => document.getElementById("job-details");

async function jobAction(id, action) {
  try { await api(`/jobs/${id}/${action}`, { json: {} }); loadJobs(); }
  catch (e) { toast(e.message, true); }
}
async function jobStop(id, gracefully = true) {
  try { await api(`/jobs/${id}/stop`, { json: { gracefully } }); loadJobs(); }
  catch (e) { toast(e.message, true); }
}
async function jobQueue(id, queued) {
  try {
    await api(`/jobs/${id}/${queued ? "dequeue" : "enqueue"}`, { method: "PUT" });
    loadJobs();
  } catch (e) { toast(e.message, true); }
}
async function deleteJob(id) {
  try {
    await api("/jobs/" + id, { method: "DELETE" });
    if (jobsSelectedId === id) { jobsSelectedId = null; jobDetailsEl().innerHTML = ""; }
    loadJobs();
  } catch (e) { toast(e.message, true); }
}

/* -- new job ------------------------------------------------------------- */
function openJobDialog() {
  const dialog = document.getElementById("job-dialog");
  dialog.innerHTML = `<h3>New job</h3>
    <label>Name</label><input id="jd-name" value="my training">
    <label>Description</label><input id="jd-desc" value="">
    <label>Start at <span class="muted">(optional timed start)</span></label>
    <input id="jd-start" type="datetime-local">
    <label>Stop at <span class="muted">(optional timed stop)</span></label>
    <input id="jd-stop" type="datetime-local">
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="createJob()">Create</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
async function createJob() {
  try {
    const body = { name: document.getElementById("jd-name").value,
                   description: document.getElementById("jd-desc").value };
    const start = document.getElementById("jd-start").value;
    const stop = document.getElementById("jd-stop").value;
    if (start) body.startAt = fromLocalInput(start);
    if (stop) body.stopAt = fromLocalInput(stop);
    const job = await api("/jobs", { json: body });
    document.getElementById("job-dialog").close();
    toast("job created"); jobsSelectedId = job.id; loadJobs();
  } catch (e) { toast(e.message, true); }
}

/* -- details drawer ------------------------------------------------------ */
function openJobDetails(id) { jobsSelectedId = id; drawJobDetails(); }

async function drawJobDetails() {
  const el = jobDetailsEl();
  if (!el || jobsSelectedId === null) return;
  let job, tasks;
  try {
    [job, tasks] = await Promise.all([
      api("/jobs/" + jobsSelectedId),
      api("/tasks?job_id=" + jobsSelectedId)]);
  } catch (e) { return toast(e.message, true); }
  job.tasks = tasks;
  // the 5s poll rebuilds this drawer; keep an open log visible across it
  const prevLog = document.getElementById("task-log");
  const logState = prevLog && prevLog.style.display !== "none"
    ? { text: prevLog.textContent, scroll: prevLog.scrollTop } : null;
  el.innerHTML = `<div class="card">
    <div class="row">
      <h3 style="margin:0">${esc(job.name)} <span class="muted">#${job.id}</span></h3>
      <span class="badge ${esc(job.status)}">${esc(job.status)}</span>
      ${job.isQueued ? '<span class="badge on">queued</span>' : ""}
      <span style="flex:1"></span>
      <button class="ghost small" onclick="openJobEditDialog(${job.id})">edit job</button>
      <button class="ghost small"
        onclick="jobsSelectedId=null;jobDetailsEl().innerHTML=''">close</button>
    </div>
    <p class="muted" style="margin:.3rem 0">${esc(job.description || "")}
      ${job.startAt ? `· starts ${fmtDt(job.startAt)}` : ""}
      ${job.stopAt ? `· stops ${fmtDt(job.stopAt)}` : ""}</p>
    <table><tr><th>task</th><th>host</th><th>pid</th><th>status</th>
      <th>command</th><th></th></tr>
    ${(job.tasks || []).map(t => `<tr>
      <td>${t.id}</td><td>${esc(t.hostname)}</td><td>${t.pid ?? ""}</td>
      <td><span class="badge ${esc(t.status)}">${esc(t.status)}</span></td>
      <td class="kv" title="${esc(t.fullCommand)}">${esc((t.fullCommand || t.command).slice(0, 48))}</td>
      <td class="row">
        <button class="ghost small" onclick="taskSpawn(${t.id})">spawn</button>
        <button class="ghost small" onclick="taskTerminate(${t.id}, true)"
          title="SIGINT — lets the training checkpoint">int</button>
        <button class="ghost small" onclick="taskTerminate(${t.id}, null)"
          title="SIGTERM">term</button>
        <button class="ghost small danger" onclick="taskTerminate(${t.id}, false)"
          title="SIGKILL">kill</button>
        <button class="ghost small" onclick="showTaskLog(${t.id})">log</button>
        <button class="ghost small" onclick="openTaskEditDialog(${t.id})">edit</button>
        <button class="ghost small danger" onclick="taskDelete(${t.id})">✕</button>
      </td></tr>`).join("")}
    </table>
    <pre class="log" id="task-log" style="display:none;margin-top:.8rem"></pre>
    <div class="row" style="margin-top:.8rem">
      <button class="ghost" onclick="openTaskCreateDialog(${job.id})">+ Add task</button>
      <button class="ghost" onclick="openTemplateDialog(${job.id})">
        + Tasks from template</button>
    </div>
  </div>`;
  if (logState) {
    const logEl = document.getElementById("task-log");
    logEl.style.display = "block";
    logEl.textContent = logState.text;
    logEl.scrollTop = logState.scroll;
  }
}

function openJobEditDialog(id) {
  api("/jobs/" + id).then(job => {
    const dialog = document.getElementById("job-dialog");
    dialog.innerHTML = `<h3>Edit job #${job.id}</h3>
      <label>Name</label><input id="jd-name" value="${esc(job.name)}">
      <label>Description</label><input id="jd-desc" value="${esc(job.description || "")}">
      <label>Start at</label><input id="jd-start" type="datetime-local"
        value="${job.startAt ? toLocalInput(new Date(job.startAt)) : ""}">
      <label>Stop at</label><input id="jd-stop" type="datetime-local"
        value="${job.stopAt ? toLocalInput(new Date(job.stopAt)) : ""}">
      <div class="row" style="margin-top:1rem">
        <button class="primary" onclick="saveJob(${job.id})">Save</button>
        <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
      </div>`;
    dialog.showModal();
  }).catch(e => toast(e.message, true));
}
async function saveJob(id) {
  try {
    const start = document.getElementById("jd-start").value;
    const stop = document.getElementById("jd-stop").value;
    await api("/jobs/" + id, { method: "PUT", json: {
      name: document.getElementById("jd-name").value,
      description: document.getElementById("jd-desc").value,
      startAt: start ? fromLocalInput(start) : null,
      stopAt: stop ? fromLocalInput(stop) : null } });
    document.getElementById("job-dialog").close();
    loadJobs();
  } catch (e) { toast(e.message, true); }
}

/* -- per-task operations ------------------------------------------------- */
async function taskSpawn(id) {
  try { await api(`/tasks/${id}/spawn`, { json: {} }); drawJobDetails(); }
  catch (e) { toast(e.message, true); }
}
async function taskTerminate(id, gracefully) {
  try {
    await api(`/tasks/${id}/terminate`, { json: { gracefully } });
    drawJobDetails();
  } catch (e) { toast(e.message, true); }
}
async function taskDelete(id) {
  try {
    await api("/tasks/" + id, { method: "DELETE" }); drawJobDetails();
  } catch (e) { toast(e.message, true); }
}
async function showTaskLog(taskId) {
  const el = document.getElementById("task-log");
  el.style.display = "block"; el.textContent = "loading…";
  try {
    el.textContent = (await api(`/tasks/${taskId}/log?tail=200`)).log || "(empty)";
  } catch (e) { el.textContent = e.message; }
}

/* -- segment editor rows (reference TaskCreate.vue env/param rows) ------- */
function segRowsHtml(kind, items) {
  return `<div id="seg-${kind}">` + items.map((seg, i) => `
    <div class="seg-row">
      <input placeholder="name" class="kv" data-kind="${kind}" data-field="name"
        value="${esc(seg.name || "")}">
      <input placeholder="value" class="kv" data-kind="${kind}" data-field="value"
        value="${esc(seg.value || "")}">
      <button class="ghost small danger" onclick="this.parentElement.remove()">✕</button>
    </div>`).join("") + `</div>
    <button class="ghost small" onclick="addSegRow('${kind}')">+ ${
      kind.startsWith("env") ? "env var" : "parameter"}</button>`;
}
function addSegRow(kind) {
  const div = document.createElement("div");
  div.className = "seg-row";
  div.innerHTML = `
    <input placeholder="name" class="kv" data-kind="${kind}" data-field="name">
    <input placeholder="value" class="kv" data-kind="${kind}" data-field="value">
    <button class="ghost small danger" onclick="this.parentElement.remove()">✕</button>`;
  document.getElementById("seg-" + kind).appendChild(div);
}
function collectSegRows(kind) {
  return [...document.querySelectorAll(`#seg-${kind} .seg-row`)].map(row => ({
    name: row.querySelector('[data-field="name"]').value.trim(),
    value: row.querySelector('[data-field="value"]').value,
  })).filter(seg => seg.name);
}
function hostnameOptions(current) {
  const known = jobsHostnames.includes(current) || !current;
  return jobsHostnames.map(h =>
    `<option ${h === current ? "selected" : ""}>${esc(h)}</option>`).join("") +
    (known ? "" : `<option selected>${esc(current)}</option>`);
}

/* -- add one task -------------------------------------------------------- */
function openTaskCreateDialog(jobId) {
  const dialog = document.getElementById("job-dialog");
  dialog.innerHTML = `<h3>Add task</h3>
    <label>Host</label><select id="td-host">${hostnameOptions()}</select>
    <label>Command</label><input id="td-cmd" class="kv" value="python3 train.py">
    <label>Chips <span class="muted">(comma-separated indices, sets the chip
      visibility env for the process)</span></label>
    <input id="td-chips" class="kv" placeholder="0,1,2,3">
    <label>Environment variables</label>
    ${segRowsHtml("env", [])}
    <label>Parameters <span class="muted">(appended as --name=value)</span></label>
    ${segRowsHtml("param", [])}
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="createTask(${jobId})">Add</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
async function createTask(jobId) {
  try {
    const body = {
      jobId,
      hostname: document.getElementById("td-host").value,
      command: document.getElementById("td-cmd").value,
      envVariables: collectSegRows("env"),
      parameters: collectSegRows("param"),
    };
    const chips = document.getElementById("td-chips").value.trim();
    if (chips) body.chips = chips.split(",").map(s => parseInt(s.trim(), 10));
    await api("/tasks", { json: body });
    document.getElementById("job-dialog").close();
    drawJobDetails();
  } catch (e) { toast(e.message, true); }
}

/* -- edit task (segments add/remove) ------------------------------------- */
async function openTaskEditDialog(taskId) {
  let task;
  try { task = await api("/tasks/" + taskId); }
  catch (e) { return toast(e.message, true); }
  const dialog = document.getElementById("job-dialog");
  dialog.innerHTML = `<h3>Edit task #${task.id}</h3>
    <label>Host</label><select id="td-host">${hostnameOptions(task.hostname)}</select>
    <label>Command</label><input id="td-cmd" class="kv" value="${esc(task.command)}">
    <label>Current segments <span class="muted">(✓ keep, ✕ remove on save)</span></label>
    <div class="assign-list">${(task.cmdSegments || []).map(seg => `
      <div class="tagrow"><span class="kv">
        ${seg.type === "env_variable" ? "env" : "param"} <b>${esc(seg.name)}</b>
        = ${esc(seg.value ?? "")}</span>
        <label class="inline" style="margin:0"><input type="checkbox"
          class="td-rm" value="${esc(seg.name)}"> ✕</label></div>`).join("")
      || '<span class="muted">none</span>'}</div>
    <label>Add environment variables</label>
    ${segRowsHtml("env", [])}
    <label>Add parameters</label>
    ${segRowsHtml("param", [])}
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="saveTask(${task.id})">Save</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
async function saveTask(taskId) {
  try {
    await api("/tasks/" + taskId, { method: "PUT", json: {
      hostname: document.getElementById("td-host").value,
      command: document.getElementById("td-cmd").value,
      envVariables: collectSegRows("env"),
      parameters: collectSegRows("param"),
      removeSegments: [...document.querySelectorAll(".td-rm:checked")]
        .map(el => el.value) } });
    document.getElementById("job-dialog").close();
    drawJobDetails();
  } catch (e) { toast(e.message, true); }
}

/* -- tasks from template (reference TaskTemplateChooser + auto-fill) ----- */
async function openTemplateDialog(jobId) {
  const templates = await api("/templates").catch(() => []);
  const dialog = document.getElementById("job-dialog");
  dialog.innerHTML = `<h3>Tasks from template</h3>
    <p class="muted">One process per placement line; the server auto-fills the
    distributed wiring (coordinator address, process ids, chip visibility) for
    the chosen template.</p>
    <label>Template</label>
    <select id="tt-template">${templates.map(t =>
      `<option ${t === "jax" ? "selected" : ""}>${esc(t)}</option>`).join("")}</select>
    <label>Command</label><input id="tt-cmd" class="kv" value="python3 train.py">
    <label>Placements <span class="muted">(one per line:
      hostname[:chip,chip][@address])</span></label>
    <textarea id="tt-placements" rows="4" class="kv">${
      jobsHostnames.map(h => h + ":0,1,2,3").join("\n")}</textarea>
    <label>Options <span class="muted">(JSON, template-specific — e.g.
      {"coordinator_port": 8476})</span></label>
    <input id="tt-options" class="kv" placeholder="{}">
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="createTasksFromTemplate(${jobId})">Generate</button>
      <button class="ghost" onclick="previewTemplateTasks(${jobId})">Preview &amp; edit</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
function collectTemplateForm() {
  const placements = document.getElementById("tt-placements").value
    .split("\n").map(s => s.trim()).filter(Boolean).map(line => {
      let address = "";
      const at = line.indexOf("@");
      if (at !== -1) { address = line.slice(at + 1); line = line.slice(0, at); }
      const [hostname, chips] = line.split(":");
      const p = { hostname: hostname.trim() };
      if (address) p.address = address;
      if (chips) p.chips = chips.split(",").map(s => parseInt(s.trim(), 10));
      return p;
    });
  const optionsRaw = document.getElementById("tt-options").value.trim();
  const body = {
    template: document.getElementById("tt-template").value,
    command: document.getElementById("tt-cmd").value,
    placements };
  if (optionsRaw) body.options = JSON.parse(optionsRaw);
  return body;
}
async function createTasksFromTemplate(jobId) {
  try {
    await api(`/jobs/${jobId}/tasks_from_template`, { json: collectTemplateForm() });
    document.getElementById("job-dialog").close();
    toast("tasks generated"); drawJobDetails();
  } catch (e) { toast(e.message, true); }
}

/* per-line interactive editing of generated tasks (reference
   TaskCreate.vue:202-424: every auto-filled parameter is editable per task
   line before creation; "static" parameters fan out to all lines) */
async function previewTemplateTasks(jobId) {
  try {
    const specs = await api("/templates/preview", { json: collectTemplateForm() });
    renderTemplatePreview(jobId, specs);
  } catch (e) { toast(e.message, true); }
}
function renderTemplatePreview(jobId, specs) {
  const dialog = document.getElementById("job-dialog");
  const entries = obj => Object.entries(obj || {}).map(
    ([name, value]) => ({ name, value }));
  dialog.innerHTML = `<h3>Review generated tasks</h3>
    <p class="muted">Every generated value is editable per line; nothing is
      created until you confirm. Static parameters fan out to all lines.</p>
    ${specs.map((spec, i) => `<div class="card tpl-line" data-line="${i}">
      <b>line ${i} — ${esc(spec.hostname)}</b>
      <input type="hidden" id="tp-host-${i}" value="${esc(spec.hostname)}">
      <label>Command</label>
      <input id="tp-cmd-${i}" class="kv" value="${esc(spec.command)}">
      <label>Environment variables</label>
      ${segRowsHtml(`env-${i}`, entries(spec.env))}
      <label>Parameters</label>
      ${segRowsHtml(`param-${i}`, entries(spec.params))}
    </div>`).join("")}
    <label>Static parameter <span class="muted">(same --name=value on every
      line, reference staticParameters)</span></label>
    <div class="row">
      <input id="tp-static-name" class="kv" placeholder="name">
      <input id="tp-static-value" class="kv" placeholder="value">
      <button class="ghost small" onclick="applyStaticParameter(${specs.length})">
        Add to all lines</button>
    </div>
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="createEditedTasks(${jobId}, ${specs.length})">
        Create ${specs.length} task${specs.length === 1 ? "" : "s"}</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
}
function applyStaticParameter(lines) {
  let name = document.getElementById("tp-static-name").value.trim();
  const value = document.getElementById("tp-static-value").value;
  if (!name) return toast("static parameter needs a name", true);
  // parameter names carry their dashes in full_command (the template
  // engine generates "--coordinator_address" etc.) — normalize bare names
  // so the fanned-out flag really reaches the command line as --name=value
  if (!name.startsWith("-")) name = "--" + name;
  for (let i = 0; i < lines; i++) {
    addSegRow(`param-${i}`);
    const rows = document.querySelectorAll(`#seg-param-${i} .seg-row`);
    const row = rows[rows.length - 1];
    row.querySelector('[data-field="name"]').value = name;
    row.querySelector('[data-field="value"]').value = value;
  }
  toast(`added ${name} to ${lines} lines`);
}
async function createEditedTasks(jobId, lines) {
  let created = 0;
  const failures = [];
  for (let i = 0; i < lines; i++) {
    try {
      await api("/tasks", { json: {
        jobId,
        hostname: document.getElementById(`tp-host-${i}`).value,
        command: document.getElementById(`tp-cmd-${i}`).value,
        envVariables: collectSegRows(`env-${i}`),
        parameters: collectSegRows(`param-${i}`) } });
      created++;
    } catch (e) { failures.push(`line ${i}: ${e.message}`); }
  }
  if (failures.length) {
    // keep the dialog (and the failed lines' edits) alive; a success toast
    // here would overwrite the error and silently lose work
    toast(`created ${created}/${lines} — ${failures.join("; ")} ` +
          `(failed lines kept for editing)`, true);
    return;
  }
  document.getElementById("job-dialog").close();
  toast(`created ${created} task${created === 1 ? "" : "s"}`);
  drawJobDetails();
}
