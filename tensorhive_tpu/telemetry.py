"""Workload-side telemetry emitter: the probe's runtime-metrics source.

The OS exposes no HBM-occupancy or utilization counters for TPU chips, so
the monitoring probe (core/monitors/probe.py) reads drop-files under
``~/.tpuhive/metrics/`` that the *workload runtime* refreshes. This module
is that publisher: training loops construct a :class:`TelemetryEmitter` and
call :meth:`sample` once per step. HBM numbers come from
``device.memory_stats()`` (PJRT's bytes_in_use / bytes_limit); duty cycle is
estimated from the device-busy fraction of the step wall time.

Together with the probe this closes the loop the reference gets for free
from ``nvidia-smi``: dashboard HBM/utilization per chip with no daemon on
the accelerator path.
"""
from __future__ import annotations

import json
import logging
import os
import tempfile
import time
from pathlib import Path
from typing import Dict, Optional

from .observability import get_registry

log = logging.getLogger(__name__)

DEFAULT_METRICS_DIR = "~/.tpuhive/metrics"

# the registry mirror of the drop-file payload: workload-side HBM/duty
# metrics share the same /api/metrics exposition surface as the control
# plane, so one Prometheus scrape covers both (observability tentpole)
_HBM_USED = get_registry().gauge(
    "tpuhive_workload_hbm_used_bytes",
    "Per-device HBM bytes in use, from the workload telemetry emitter.",
    labels=("device",))
_HBM_TOTAL = get_registry().gauge(
    "tpuhive_workload_hbm_total_bytes",
    "Per-device HBM capacity in bytes.", labels=("device",))
_DUTY = get_registry().gauge(
    "tpuhive_workload_duty_cycle_pct",
    "Per-device duty-cycle estimate over the last write window (percent).",
    labels=("device",))
_PUBLISHES = get_registry().counter(
    "tpuhive_workload_publishes_total",
    "Successful drop-file publishes by the telemetry emitter.")
_PUBLISH_FAILURES = get_registry().counter(
    "tpuhive_workload_publish_failures_total",
    "Drop-file publishes that failed (I/O errors).")
_MEMORY_STATS_FAILURES = get_registry().counter(
    "tpuhive_workload_memory_stats_failures_total",
    "device.memory_stats() calls that raised (backend without support); "
    "a fleet silently losing HBM metrics shows up here.")


class TelemetryEmitter:
    def __init__(
        self,
        name: str = "workload",
        metrics_dir: Optional[str] = None,
        min_write_interval_s: float = 1.0,
    ) -> None:
        directory = metrics_dir or os.environ.get("TPUHIVE_METRICS_DIR") \
            or DEFAULT_METRICS_DIR
        self.path = Path(os.path.expanduser(directory)) / f"{name}-{os.getpid()}.json"
        self.min_write_interval_s = min_write_interval_s
        self._last_write = 0.0
        self._window_start: Optional[float] = None
        self._busy_accum_s = 0.0

    def sample(self, step_time_s: Optional[float] = None,
               device_busy_s: Optional[float] = None) -> Optional[Dict]:
        """Accumulate busy time and (rate-limited) publish metrics.

        ``step_time_s``/``device_busy_s`` feed the duty-cycle estimate: a
        synchronous training loop is assumed fully busy between dispatch and
        block_until_ready. Busy time accumulates across EVERY call so that
        steps shorter than the write interval still sum to the true busy
        fraction of the window (one step over the whole window would
        undercount a ~100%-busy device to a few percent).
        """
        now = time.monotonic()
        if self._window_start is None:
            self._window_start = now - (step_time_s or 0.0)
        busy = device_busy_s if device_busy_s is not None else step_time_s
        if busy is not None:
            self._busy_accum_s += busy
        if now - self._last_write < self.min_write_interval_s:
            return None

        duty = None
        window = now - self._window_start
        if step_time_s is not None and window > 0:
            duty = max(0.0, min(100.0, 100.0 * self._busy_accum_s / window))
        self._window_start = now
        self._busy_accum_s = 0.0

        metrics = self.collect(duty_cycle_pct=duty)
        if metrics:
            self._mirror_to_registry(metrics)
            self._write(metrics)
            self._last_write = now
        return metrics

    @staticmethod
    def _mirror_to_registry(metrics: Dict[str, Dict]) -> None:
        """Copy the drop-file payload into the in-process metrics registry
        so training-loop telemetry appears on /api/metrics alongside the
        control-plane instrumentation."""
        for device, values in metrics.items():
            if values.get("hbm_used_bytes") is not None:
                _HBM_USED.labels(device=device).set(values["hbm_used_bytes"])
            if values.get("hbm_total_bytes") is not None:
                _HBM_TOTAL.labels(device=device).set(values["hbm_total_bytes"])
            if values.get("duty_cycle_pct") is not None:
                _DUTY.labels(device=device).set(values["duty_cycle_pct"])

    @staticmethod
    def collect(duty_cycle_pct: Optional[float] = None) -> Dict[str, Dict]:
        """One entry per local device index, probe drop-file schema."""
        import jax

        metrics: Dict[str, Dict] = {}
        try:
            devices = jax.local_devices()
        except RuntimeError:
            return metrics
        for device in devices:
            stats = {}
            try:
                stats = device.memory_stats() or {}
            except Exception:
                # backends without memory_stats (CPU) report None fields —
                # tolerated, but counted + debug-logged so HBM metrics
                # silently missing from a dashboard is diagnosable (TH-E)
                _MEMORY_STATS_FAILURES.inc()
                log.debug("memory_stats unavailable for device %s",
                          device, exc_info=True)
            metrics[str(device.local_hardware_id
                        if hasattr(device, "local_hardware_id") else device.id)] = {
                "hbm_used_bytes": stats.get("bytes_in_use"),
                "hbm_total_bytes": stats.get("bytes_limit"),
                "duty_cycle_pct": duty_cycle_pct,
            }
        return metrics

    def _write(self, metrics: Dict) -> None:
        """Atomic publish: the probe may read concurrently; a rename never
        exposes a torn file (the probe additionally validates JSON)."""
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(self.path.parent), suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(metrics, fh)
            os.replace(tmp, self.path)
        except OSError:
            self._discard_tmp(tmp)
            _PUBLISH_FAILURES.inc()  # I/O flakiness is expected; swallowed
        except BaseException:
            # the temp file must never survive a failed publish — json.dump
            # raises non-OSError too (a non-serializable value lands here as
            # TypeError), and each such failure used to litter the metrics
            # dir with an orphan .tmp the probe would skip but never reclaim
            self._discard_tmp(tmp)
            raise  # programming errors stay loud
        else:
            _PUBLISHES.inc()

    @staticmethod
    def _discard_tmp(tmp: str) -> None:
        try:
            os.unlink(tmp)
        except OSError:
            pass

    def close(self) -> None:
        """Remove the drop-file (job teardown)."""
        try:
            self.path.unlink()
        except OSError:
            pass
