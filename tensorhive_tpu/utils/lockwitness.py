"""Runtime lock witness: the dynamic half of the TH-LOCK story.

The static analyzer (tools/analysis/rules/locks.py) builds a lock-order
graph by reading the code; this module builds one by *running* it. Lock
construction sites opt in by naming their lock through this factory::

    self._lock = lockwitness.Lock("SlotEngine._lock")
    _engine_lock = lockwitness.Lock("tensorhive_tpu.serving._engine_lock")

With ``TPUHIVE_LOCK_WITNESS=1`` (or :func:`enable` in tests) each named
lock is wrapped in an instrumented proxy that records, per acquire:

* the **per-thread held-set** — which named locks this thread already
  holds;
* the **observed-order graph** — an edge ``A -> B`` whenever ``B`` is
  acquired while ``A`` is held (same-name re-entry is skipped: lock
  identity is class-level, matching the static model's granularity);
* **real inversions, at acquire time** — if the reverse edge ``B -> A``
  was ever observed, this acquisition completes an ABBA pair: recorded
  with both threads' context before anything actually deadlocks;
* **wait / hold statistics** per name, exported as the
  ``tpuhive_lock_wait_seconds{lock}`` histogram (contended acquires
  only; ``export_wait=False`` opts the metrics registry's own locks out
  so the export path cannot recurse into itself).

:func:`dump` writes the observed graph as JSON; ``python -m
tools.analysis --witness <dump>`` asserts observed edges are a subset of
the static graph — the chaos/serving smokes run with the witness on, so
every green run is an executable proof the static model over-approximates
reality instead of imagining a different program.

**Disabled (the default), the factory returns plain ``threading`` objects
— byte-identical behavior, no wrapper, no overhead.** The one exception
is ``observe_wait=True`` (the serving engine lock): a thin always-on
proxy whose fast path is a single non-blocking try-acquire, timing only
contended waits, so engine-lock contention is visible in the PR 16
history/SLO layer in production, not just under the witness.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_ENV = "TPUHIVE_LOCK_WITNESS"

#: sub-millisecond to second buckets: lock waits live far below request
#: latencies, and the interesting regressions are 100us -> 10ms creeps
WAIT_BUCKETS: Tuple[float, ...] = (0.0001, 0.0005, 0.001, 0.005, 0.01,
                                   0.05, 0.1, 0.5, 1.0)

_forced: Optional[bool] = None
_wait_family = None
_wait_family_lock = threading.Lock()


def witness_enabled() -> bool:
    """True when lock construction should produce witnessed proxies."""
    if _forced is not None:
        return _forced
    return os.environ.get(_ENV, "") == "1"


def enable() -> None:
    """Force the witness on for locks constructed after this call (tests;
    production opts in via the environment before import)."""
    global _forced
    _forced = True


def disable() -> None:
    global _forced
    _forced = None


# -- wait-time export ---------------------------------------------------------
def _wait_histogram():
    global _wait_family
    if _wait_family is None:
        with _wait_family_lock:
            if _wait_family is None:
                from ..observability import get_registry

                _wait_family = get_registry().histogram(
                    "tpuhive_lock_wait_seconds",
                    "Time spent waiting for a named lock "
                    "(contended acquires only)",
                    labels=("lock",), buckets=WAIT_BUCKETS)
    return _wait_family


def observe_wait(name: str, seconds: float) -> None:
    """One contended-acquire wait for ``name`` into the export histogram.
    Guarded against reentry: the observation itself takes the registry
    family lock, which must never observe its own wait."""
    tls = _state.tls
    if getattr(tls, "in_observer", False):
        return
    tls.in_observer = True
    try:
        _wait_histogram().labels(lock=name).observe(seconds)
    except Exception:  # thive: disable=TH-E
        pass        # metrics must never take the data plane down
    finally:
        tls.in_observer = False


# -- witness state ------------------------------------------------------------
class _WitnessState:
    """Process-global observed-order graph + per-name statistics. The
    internal mutex is a plain unnamed lock: a leaf by construction (held
    only across dict updates, never across user code), so it cannot
    appear in its own graph."""

    def __init__(self) -> None:
        self.mutex = threading.Lock()
        self.tls = threading.local()
        #: (from name, to name) -> observation count
        self.edges: Dict[Tuple[str, str], int] = {}
        self.inversions: List[Dict[str, Any]] = []
        self.stats: Dict[str, Dict[str, float]] = {}

    # per-thread held stack: [name, id(lock), t_acquired]
    def held(self) -> List[List[Any]]:
        stack = getattr(self.tls, "held", None)
        if stack is None:
            stack = []
            self.tls.held = stack
        return stack

    def reset(self) -> None:
        with self.mutex:
            self.edges.clear()
            self.inversions.clear()
            self.stats.clear()

    def _stat_locked(self, name: str) -> Dict[str, float]:
        stat = self.stats.get(name)
        if stat is None:
            stat = {"acquisitions": 0, "contended": 0, "wait_total_s": 0.0,
                    "wait_max_s": 0.0, "hold_total_s": 0.0,
                    "hold_max_s": 0.0}
            self.stats[name] = stat
        return stat

    def record_acquired(self, name: str, lock_id: int, waited: float,
                        contended: bool) -> None:
        held = self.held()
        held_names = [entry[0] for entry in held]
        now = time.perf_counter()
        # re-acquiring a lock this thread already holds (reentrant, or the
        # class-level identity blurring two instances) imposes no NEW
        # ordering: record stats only, no edges, no inversion — mirroring
        # the static model, which skips held targets when building edges
        reacquire = name in held_names
        with self.mutex:
            stat = self._stat_locked(name)
            stat["acquisitions"] += 1
            if contended:
                stat["contended"] += 1
                stat["wait_total_s"] += waited
                stat["wait_max_s"] = max(stat["wait_max_s"], waited)
            for other in held_names:
                if reacquire:
                    break
                if (name, other) in self.edges:
                    # the reverse edge exists: this acquire completes an
                    # ABBA inversion — record it BEFORE the deadlock can
                    if (other, name) not in self.edges:
                        self.inversions.append({
                            "cycle": [other, name],
                            "thread": threading.current_thread().name,
                            "held": list(held_names),
                            "acquiring": name,
                        })
                self.edges[(other, name)] = \
                    self.edges.get((other, name), 0) + 1
        held.append([name, lock_id, now])

    def record_released(self, name: str, lock_id: int) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == lock_id:
                entry = held.pop(i)
                hold = time.perf_counter() - entry[2]
                with self.mutex:
                    stat = self._stat_locked(name)
                    stat["hold_total_s"] += hold
                    stat["hold_max_s"] = max(stat["hold_max_s"], hold)
                return

    def is_owned(self, lock_id: int) -> bool:
        return any(entry[1] == lock_id for entry in self.held())


_state = _WitnessState()


def reset() -> None:
    """Clear the observed graph and statistics (tests)."""
    _state.reset()


def snapshot() -> Dict[str, Any]:
    """The witness graph as plain data (stable shape: the comparator and
    the smokes consume this)."""
    with _state.mutex:
        return {
            "enabled": witness_enabled(),
            "edges": sorted([a, b, n] for (a, b), n in
                            _state.edges.items()),
            "inversions": [dict(inv) for inv in _state.inversions],
            "locks": {name: dict(stat)
                      for name, stat in sorted(_state.stats.items())},
        }


def dump(path: str) -> Dict[str, Any]:
    """Write :func:`snapshot` to ``path`` as JSON; returns the snapshot."""
    data = snapshot()
    with open(path, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return data


# -- the instrumented lock ----------------------------------------------------
class _WitnessLock:
    """A named lock proxy recording held-sets, order edges and wait/hold
    times. Wraps a plain (or reentrant) ``threading`` lock; context
    manager, ``acquire(blocking, timeout)`` and ``locked()`` behave like
    the wrapped object."""

    __slots__ = ("name", "_lock", "_reentrant", "_export")

    def __init__(self, name: str, inner: Any, reentrant: bool,
                 export: bool) -> None:
        self.name = name
        self._lock = inner
        self._reentrant = reentrant
        self._export = export

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._lock.acquire(False):
            _state.record_acquired(self.name, id(self), 0.0, False)
            return True
        if not blocking:
            return False
        start = time.perf_counter()
        if timeout is not None and timeout >= 0:
            ok = self._lock.acquire(True, timeout)
        else:
            ok = self._lock.acquire(True)
        if not ok:
            return False
        waited = time.perf_counter() - start
        _state.record_acquired(self.name, id(self), waited, True)
        if self._export:
            observe_wait(self.name, waited)
        return True

    def release(self) -> None:
        _state.record_released(self.name, id(self))
        self._lock.release()

    def __enter__(self) -> "_WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    # threading.Condition probes ownership through this when wrapping a
    # foreign lock; the default probe (try-acquire) would misreport a
    # reentrant inner lock, so answer from the witness held-set
    def _is_owned(self) -> bool:
        return _state.is_owned(id(self))


class _ObservedLock:
    """Always-on wait observation for ONE hot lock (the serving engine):
    no witness graph, no held-set — a non-blocking try on the fast path,
    a timed wait plus one histogram observation under contention."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str, inner: Any) -> None:
        self.name = name
        self._lock = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._lock.acquire(False):
            return True
        if not blocking:
            return False
        start = time.perf_counter()
        if timeout is not None and timeout >= 0:
            ok = self._lock.acquire(True, timeout)
        else:
            ok = self._lock.acquire(True)
        if ok:
            observe_wait(self.name, time.perf_counter() - start)
        return ok

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "_ObservedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()


# -- the factory --------------------------------------------------------------
# Terminal names deliberately mirror threading.Lock/RLock/Condition so the
# static lock vocabulary (dataflow.LOCK_FACTORIES) recognizes construction
# sites unchanged; the name argument is the contract that makes the static
# and runtime graphs speak about the same lock.
def Lock(name: Optional[str] = None, *, observe_wait: bool = False,
         export_wait: bool = True):
    """A mutex. Plain ``threading.Lock()`` unless the witness is enabled
    (named proxy) or ``observe_wait=True`` (always-on wait histogram)."""
    if name and witness_enabled():
        return _WitnessLock(name, threading.Lock(), False, export_wait)
    if name and observe_wait:
        return _ObservedLock(name, threading.Lock())
    return threading.Lock()


def RLock(name: Optional[str] = None, *, observe_wait: bool = False,
          export_wait: bool = True):
    if name and witness_enabled():
        return _WitnessLock(name, threading.RLock(), True, export_wait)
    if name and observe_wait:
        return _ObservedLock(name, threading.RLock())
    return threading.RLock()


def Condition(name: Optional[str] = None):
    """A condition variable. Witnessed, its internal lock is a named
    proxy: ``wait()`` releases and re-acquires through the proxy, so the
    held-set stays truthful across waits."""
    if name and witness_enabled():
        return threading.Condition(
            _WitnessLock(name, threading.Lock(), False, True))
    return threading.Condition()
