"""Preemption-safe long training run for the queue scheduler (config 4).

The TPU-native analog of the reference's DeepSpeech long-training scenario
(reference examples/deepspeech/README.md): the job sits in the scheduler
queue, launches whenever its chips are free of other users' reservations,
and is **preempted with SIGINT** when a foreign reservation approaches
(core/services/job_scheduling.py sync_running_from_queue; the reference's
JobSchedulingService.py:254-283).

This script makes preemption lossless:

* SIGINT/SIGTERM set a flag; the loop checkpoints (orbax) and exits 0;
* on the next launch the loop restores the latest step and continues —
  run it twice with the same ``--checkpoint-dir`` and it picks up where the
  preemption stopped.

Enqueue it with the `jax` template:

    POST /jobs                      {"name": "long pretrain"}
    POST /jobs/<id>/tasks_from_template
         {"template": "jax", "command": "python3 examples/queued_training/train.py
          --preset t2t-big --steps 500000 --checkpoint-dir ~/ckpt/pretrain",
          "placements": [{"hostname": "v5e8-w0", "chips": [0,1,2,3]},
                         {"hostname": "v5e8-w1", "chips": [0,1,2,3]}]}
    PUT  /jobs/<id>/enqueue
"""
import argparse
import os
import signal
import sys
import time

import jax

from tensorhive_tpu.models.transformer import PRESETS, TransformerLM
from tensorhive_tpu.parallel.mesh import best_mesh_shape, make_mesh
from tensorhive_tpu.train import (
    TrainConfig,
    abstract_train_state,
    init_train_state,
    make_train_step,
    restore_checkpoint,
    save_checkpoint,
    synthetic_batch,
)

_preempted = False


def _request_stop(signum, frame):
    global _preempted
    _preempted = True


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="t2t-big", choices=sorted(PRESETS))
    parser.add_argument("--steps", type=int, default=500_000)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--seq_len", type=int, default=512)
    parser.add_argument("--checkpoint-dir", default="~/tpuhive-ckpt")
    parser.add_argument("--checkpoint-every", type=int, default=200)
    parser.add_argument("--log-every", type=int, default=25)
    # auto-filled by the `jax` template:
    parser.add_argument("--coordinator_address", default=None)
    parser.add_argument("--num_processes", type=int, default=None)
    parser.add_argument("--process_id", type=int, default=None)
    args = parser.parse_args()

    if args.coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    signal.signal(signal.SIGINT, _request_stop)
    signal.signal(signal.SIGTERM, _request_stop)

    checkpoint_dir = os.path.abspath(os.path.expanduser(args.checkpoint_dir))
    model_config = PRESETS[args.preset]
    train_config = TrainConfig(batch_size=args.batch_size, seq_len=args.seq_len,
                               warmup_steps=min(100, max(1, args.steps // 10)),
                               total_steps=args.steps)
    mesh = make_mesh(**best_mesh_shape(len(jax.devices())))
    key = jax.random.PRNGKey(0)
    start_step = 0
    try:
        # resume restores into ABSTRACT templates: no throwaway initialized
        # state alive next to the restored copy (2× peak would OOM large
        # presets exactly on the preemption-resume path)
        abstract_params, abstract_opt = abstract_train_state(
            model_config, train_config, mesh)
        start_step, params, opt_state = restore_checkpoint(
            checkpoint_dir, abstract_params, abstract_opt)
        print(f"resumed from step {start_step} ({checkpoint_dir})", flush=True)
    except FileNotFoundError:
        params, opt_state = init_train_state(key, model_config, train_config,
                                             mesh)
        print(f"fresh run ({args.preset}: "
              f"{TransformerLM.param_count(params) / 1e6:.1f}M params)", flush=True)

    step_fn = make_train_step(model_config, train_config, mesh)
    step = start_step
    last_saved = start_step
    key = jax.random.fold_in(key, start_step)

    def checkpoint(at_step: int) -> None:
        # orbax refuses to re-save an existing step; dedupe so a preemption
        # landing on a checkpoint boundary (or an already-finished run) is
        # still a clean exit
        nonlocal last_saved
        if at_step != last_saved:
            save_checkpoint(checkpoint_dir, at_step, params, opt_state)
            last_saved = at_step

    while step < args.steps and not _preempted:
        key, data_key = jax.random.split(key)
        tokens = synthetic_batch(data_key, train_config, model_config.vocab_size)
        started = time.perf_counter()
        params, opt_state, metrics = step_fn(params, opt_state, tokens)
        step += 1
        if args.log_every and step % args.log_every == 0:
            # reading the loss forces a host sync — only do it on log steps
            # so dispatch of step N+1 overlaps execution of step N otherwise
            print(f"step {step}/{args.steps} loss={float(metrics['loss']):.4f} "
                  f"({(time.perf_counter() - started) * 1e3:.0f} ms)", flush=True)
        if step % args.checkpoint_every == 0:
            checkpoint(step)

    checkpoint(step)
    if _preempted:
        print(f"preempted at step {step}: checkpoint saved, exiting cleanly",
              flush=True)
        sys.exit(0)
    print(f"finished {args.steps} steps", flush=True)


if __name__ == "__main__":
    main()
