"""DDP training job for a v5e TPU VM (acceptance config 2).

Spawned by the `torch-xla` template (core/templates.py `_torch_xla`), which
sets ``PJRT_DEVICE=TPU``, ``MASTER_ADDR``/``MASTER_PORT``, ``NODE_RANK``,
``WORLD_SIZE`` and the chip-visibility env per worker — the TPU-native
successor of the reference's torch.distributed rank/world-size template
(reference examples/PyTorch/README.md:26-56, DCGAN over gloo).

Two runtime paths, chosen by what the host offers:

* **torch-xla present** (a real TPU VM): ``torch_xla.launch`` forks one
  process per visible chip under PJRT; DDP gradients ride the XLA backend.
* **CPU fallback** (CI, the fake cluster, laptops): plain
  ``torch.distributed`` over gloo using the exact same template env, so the
  example is end-to-end runnable anywhere — including single-process when no
  MASTER_ADDR is set.
"""
import argparse
import os

import torch
import torch.distributed as dist
import torch.nn as nn


def build_model() -> nn.Module:
    # compact conv classifier standing in for the reference DCGAN workload
    return nn.Sequential(
        nn.Conv2d(1, 16, 3, stride=2, padding=1), nn.ReLU(),
        nn.Conv2d(16, 32, 3, stride=2, padding=1), nn.ReLU(),
        nn.Flatten(), nn.Linear(32 * 8 * 8, 10),
    )


def synthetic_batch(batch_size: int, generator: torch.Generator):
    images = torch.randn(batch_size, 1, 32, 32, generator=generator)
    labels = torch.randint(0, 10, (batch_size,), generator=generator)
    return images, labels


def train(device, rank: int, world_size: int, steps: int, batch_size: int,
          use_ddp: bool) -> float:
    torch.manual_seed(1234 + rank)
    generator = torch.Generator().manual_seed(5678 + rank)
    model = build_model().to(device)
    if use_ddp:
        model = nn.parallel.DistributedDataParallel(model)
    optimizer = torch.optim.Adam(model.parameters(), lr=1e-3)
    loss_fn = nn.CrossEntropyLoss()
    loss = torch.tensor(0.0)
    for step in range(steps):
        images, labels = synthetic_batch(batch_size, generator)
        images, labels = images.to(device), labels.to(device)
        optimizer.zero_grad()
        loss = loss_fn(model(images), labels)
        loss.backward()
        # DDP already all-reduced the grads; a plain step is correct on both
        # backends (xm.optimizer_step would reduce a second time under DDP)
        optimizer.step()
        if device.type == "xla":
            import torch_xla
            torch_xla.sync()
        if rank == 0 and (step + 1) % 10 == 0:
            print(f"step {step + 1}/{steps} loss={loss.item():.4f}", flush=True)
    return float(loss.item())


def run_cpu(steps: int, batch_size: int) -> None:
    """gloo path driven by the torch-xla template env (MASTER_ADDR et al.)."""
    rank = int(os.environ.get("NODE_RANK", "0"))
    world_size = int(os.environ.get("WORLD_SIZE", "1"))
    use_ddp = world_size > 1
    if use_ddp:
        dist.init_process_group(
            "gloo",
            init_method="tcp://{}:{}".format(
                os.environ["MASTER_ADDR"], os.environ["MASTER_PORT"]),
            rank=rank, world_size=world_size)
    loss = train(torch.device("cpu"), rank, world_size, steps, batch_size, use_ddp)
    if use_ddp:
        dist.destroy_process_group()
    if rank == 0:
        print(f"done (cpu/gloo world={world_size}): loss={loss:.4f}", flush=True)


def run_tpu(steps: int, batch_size: int) -> None:
    """torch-xla PJRT path: one process per chip visible to this worker.
    Uses the torch_xla.runtime API (the xm.xrt_* generation was removed in
    the same releases that introduced torch_xla.launch)."""
    import torch_xla
    import torch_xla.runtime as xr
    import torch_xla.distributed.xla_backend  # noqa: F401  (registers 'xla')

    def _mp_fn(index):
        dist.init_process_group("xla", init_method="xla://")
        device = torch_xla.device()
        loss = train(device, xr.global_ordinal(), xr.world_size(),
                     steps, batch_size, use_ddp=True)
        if xr.global_ordinal() == 0:
            print(f"done (tpu world={xr.world_size()}): loss={loss:.4f}",
                  flush=True)

    torch_xla.launch(_mp_fn)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch_size", type=int, default=32)
    args = parser.parse_args()
    try:
        import torch_xla  # noqa: F401
        has_xla = True
    except ImportError:
        has_xla = False
    if has_xla and os.environ.get("PJRT_DEVICE") == "TPU":
        run_tpu(args.steps, args.batch_size)
    else:
        run_cpu(args.steps, args.batch_size)


if __name__ == "__main__":
    main()
