"""Multi-slice training across TPU slices joined over DCN (config 5).

Spawned by the `multislice` template (core/templates.py `_multislice`) with
one task per slice; the template sets ``MEGASCALE_COORDINATOR_ADDRESS``,
``MEGASCALE_NUM_SLICES``, ``MEGASCALE_SLICE_ID`` and ``MEGASCALE_PORT``, and
the TPU runtime fans each slice-0 command out to the slice's own workers.
The reference had nothing at this scale — its largest topology was N
independent processes over gloo (examples/PyTorch/README.md).

Mesh layout follows the scaling-book recipe: the **dp axis spans slices**
(only gradient all-reduces cross DCN), fsdp/tp stay inside a slice on ICI,
ring-attention sp (when used) also stays inside a slice.

Runnable anywhere: with megascale env + TPUs it initializes
``jax.distributed`` and spans slices; without them it falls back to a
single-process run with the same dp-outermost mesh over local devices, so
CI and the fake cluster can execute the identical command line.
"""
import argparse
import os

import jax

from tensorhive_tpu.models.transformer import PRESETS
from tensorhive_tpu.parallel.mesh import make_mesh
from tensorhive_tpu.telemetry import TelemetryEmitter
from tensorhive_tpu.train import TrainConfig, train_loop


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="1b", choices=sorted(PRESETS))
    parser.add_argument("--steps", type=int, default=100_000)
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--seq_len", type=int, default=2048)
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel factor inside each slice")
    args = parser.parse_args()

    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    if "MEGASCALE_COORDINATOR_ADDRESS" in os.environ:
        # megascale env is read by the TPU runtime itself; jax.distributed
        # autodetects coordinator/process topology on Cloud TPU
        jax.distributed.initialize()

    n_devices = len(jax.devices())
    # dp across slices (DCN), fsdp absorbs the rest of each slice (ICI)
    dp = num_slices if n_devices % num_slices == 0 else 1
    mesh = make_mesh(dp=dp, fsdp=-1, tp=args.tp)
    if jax.process_index() == 0:
        print(f"mesh over {n_devices} devices: dp={dp} (DCN axis) "
              f"tp={args.tp}, fsdp=rest (ICI)", flush=True)

    telemetry = TelemetryEmitter(name="multislice")
    try:
        metrics = train_loop(
            PRESETS[args.preset],
            TrainConfig(batch_size=args.batch_size, seq_len=args.seq_len,
                        warmup_steps=min(100, max(1, args.steps // 10)),
                        total_steps=args.steps),
            mesh=mesh,
            num_steps=args.steps,
            telemetry=telemetry,
            sync_every=10,      # pipeline step dispatch; sync per telemetry window
        )
        if jax.process_index() == 0:
            print(f"final: loss={metrics['loss']:.4f} "
                  f"steps/s={metrics['steps_per_sec']:.3f}", flush=True)
    finally:
        telemetry.close()


if __name__ == "__main__":
    main()
