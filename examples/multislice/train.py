"""Multi-slice training across TPU slices joined over DCN (config 5).

Spawned by the `multislice` template (core/templates.py `_multislice`) with
one task per slice; the template sets ``MEGASCALE_COORDINATOR_ADDRESS``,
``MEGASCALE_NUM_SLICES``, ``MEGASCALE_SLICE_ID`` and ``MEGASCALE_PORT``, and
the TPU runtime fans each slice-0 command out to the slice's own workers.
The reference had nothing at this scale — its largest topology was N
independent processes over gloo (examples/PyTorch/README.md).

Mesh layout follows the scaling-book recipe: the **dp axis spans slices**
(only gradient all-reduces cross DCN), fsdp/tp stay inside a slice on ICI,
ring-attention sp (when used) also stays inside a slice.

Runnable anywhere: with megascale env + TPUs it initializes
``jax.distributed`` and spans slices; without them it falls back to a
single-process run with the same dp-outermost mesh over local devices, so
CI and the fake cluster can execute the identical command line.
"""
import argparse
import os

import jax

from tensorhive_tpu.models.transformer import PRESETS
from tensorhive_tpu.parallel.mesh import make_mesh
from tensorhive_tpu.telemetry import TelemetryEmitter
from tensorhive_tpu.train import TrainConfig, train_loop


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="1b", choices=sorted(PRESETS))
    parser.add_argument("--steps", type=int, default=100_000)
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--seq_len", type=int, default=2048)
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel factor inside each slice")
    args = parser.parse_args()

    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    coordinator = os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    if coordinator and os.environ.get("JAX_PLATFORMS") == "cpu":
        # CPU dryrun (one "slice" per process over virtual devices): there
        # is no TPU runtime to autodetect topology from, so parse the
        # megascale env the template generated (core/templates.py
        # _multislice) into explicit jax.distributed wiring. The config
        # API, not just the env var, pins the platform: plugin backends
        # (axon) override JAX_PLATFORMS
        jax.config.update("jax_platforms", "cpu")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_slices,
            process_id=int(os.environ.get("MEGASCALE_SLICE_ID", "0")))
    elif coordinator:
        # megascale env is read by the TPU runtime itself; jax.distributed
        # autodetects coordinator/process topology on Cloud TPU
        jax.distributed.initialize()

    n_devices = len(jax.devices())
    # dp across slices (DCN), fsdp absorbs the rest of each slice (ICI)
    dp = num_slices if n_devices % num_slices == 0 else 1
    mesh = make_mesh(dp=dp, fsdp=-1, tp=args.tp)
    if jax.process_index() == 0:
        print(f"mesh over {n_devices} devices: dp={dp} (DCN axis) "
              f"tp={args.tp}, fsdp=rest (ICI)", flush=True)

    train_config = TrainConfig(batch_size=args.batch_size,
                               seq_len=args.seq_len,
                               warmup_steps=min(100, max(1, args.steps // 10)),
                               total_steps=args.steps)
    batches = None
    if jax.process_count() > 1:
        # multi-controller: every process must feed GLOBAL arrays — a
        # host-local synthetic batch cannot enter a jit sharded over
        # non-addressable devices
        batches = _global_synthetic_batches(
            mesh, train_config, PRESETS[args.preset].vocab_size)

    telemetry = TelemetryEmitter(name="multislice")
    try:
        metrics = train_loop(
            PRESETS[args.preset],
            train_config,
            mesh=mesh,
            num_steps=args.steps,
            telemetry=telemetry,
            sync_every=10,      # pipeline step dispatch; sync per telemetry window
            batches=batches,
        )
        # every slice reports: cross-slice agreement on the final loss is
        # the dryrun's proof that one global step ran (not N local ones)
        print(f"slice {jax.process_index()}: final "
              f"loss={metrics['loss']:.6f}", flush=True)
        if jax.process_index() == 0:
            print(f"final: loss={metrics['loss']:.4f} "
                  f"steps/s={metrics['steps_per_sec']:.3f}", flush=True)
    finally:
        telemetry.close()


def _global_synthetic_batches(mesh, train_config, vocab_size):
    """Seeded synthetic batches as GLOBAL jax.Arrays: every process computes
    the same per-step numpy batch and contributes only its addressable
    shards (make_array_from_callback) — the multi-process analog of
    train.synthetic_batch."""
    import numpy as np

    from tensorhive_tpu.parallel.mesh import batch_sharding

    sharding = batch_sharding(mesh)
    shape = (train_config.batch_size, train_config.seq_len + 1)
    step = 0
    while True:
        batch = np.random.default_rng(step).integers(
            0, vocab_size, shape, dtype=np.int32)
        yield jax.make_array_from_callback(
            shape, sharding, lambda index: batch[index])
        step += 1


if __name__ == "__main__":
    main()
