"""Multi-worker flagship training entrypoint (acceptance config 3).

Spawned by the `jax` template once per slice worker; the template-provided
params wire `jax.distributed.initialize`, after which all chips of the slice
form one mesh and the sharded train step runs data/fsdp-parallel across it.
Telemetry flows back to the manager's dashboard via the drop-file emitter.
"""
import argparse

import jax

from tensorhive_tpu.models.transformer import PRESETS
from tensorhive_tpu.parallel.mesh import best_mesh_shape, make_mesh
from tensorhive_tpu.telemetry import TelemetryEmitter
from tensorhive_tpu.train import TrainConfig, train_loop


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="t2t-base", choices=sorted(PRESETS))
    parser.add_argument("--steps", type=int, default=1000)
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--seq_len", type=int, default=1024)
    parser.add_argument("--data", default=None,
                        help="glob of raw token shards (uint16); synthetic "
                             "data when omitted")
    # auto-filled by the `jax` template:
    parser.add_argument("--coordinator_address", default=None)
    parser.add_argument("--num_processes", type=int, default=None)
    parser.add_argument("--process_id", type=int, default=None)
    args = parser.parse_args()

    if args.coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    mesh = make_mesh(**best_mesh_shape(len(jax.devices())))
    batches = None
    if args.data:
        from tensorhive_tpu.data import DataConfig, TokenDataset, prefetch_to_device
        from tensorhive_tpu.parallel.mesh import batch_sharding

        dataset = TokenDataset(DataConfig(
            pattern=args.data, seq_len=args.seq_len,
            batch_size=args.batch_size,
            vocab_size=PRESETS[args.preset].vocab_size))
        batches = prefetch_to_device(dataset, start_step=0,
                                     num_steps=args.steps,
                                     sharding=batch_sharding(mesh))
    telemetry = TelemetryEmitter(name="jax_t2t")
    try:
        metrics = train_loop(
            PRESETS[args.preset],
            TrainConfig(batch_size=args.batch_size, seq_len=args.seq_len,
                        warmup_steps=min(100, max(1, args.steps // 10)),
                        total_steps=args.steps),
            mesh=mesh,
            num_steps=args.steps,
            telemetry=telemetry,
            sync_every=10,      # pipeline step dispatch; sync per telemetry window
            batches=batches,
        )
        if jax.process_index() == 0:
            print(f"final: {metrics}")
    finally:
        telemetry.close()


if __name__ == "__main__":
    main()
