"""Tiny CPU training run used by the localhost example (acceptance config 1).

Runs the framework's own transformer trainer at toy scale so the example is
self-contained — the spawned process exercises the same train step the TPU
workloads use.
"""
import jax

from tensorhive_tpu.models.transformer import PRESETS
from tensorhive_tpu.train import TrainConfig, train_loop


def main() -> None:
    metrics = train_loop(
        PRESETS["tiny"],
        TrainConfig(batch_size=4, seq_len=64, warmup_steps=2, total_steps=30),
        num_steps=30,
        log_every=5,
    )
    print(f"done on {jax.default_backend()}: "
          f"loss={metrics['loss']:.3f} steps/s={metrics['steps_per_sec']:.2f}")


if __name__ == "__main__":
    main()
