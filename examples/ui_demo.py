"""Boot the full tpuhive stack against the in-process fake cluster.

Development/demo harness: API server + web app server + seeded data, no real
hosts needed. Gives you a browsable UI (reference: the TensorHive quickstart
`tensorhive` daemon boot, cli.py:111-148) with:

  - 2 fake v5e hosts x 4 chips with drifting telemetry
  - users:  admin / admin123   and   alice / alice123
  - a global permissive restriction, one schedule, one named restriction
  - a reservation today on vm-0:tpu:0 and a 2-host jax job

Run:  python examples/ui_demo.py   then open http://localhost:5000
"""
import math
import os
import random
import threading
import time

os.environ.setdefault("TPUHIVE_PYTEST", "1")   # in-memory DB

from tensorhive_tpu.app.server import AppServer                     # noqa: E402
from tensorhive_tpu.api.server import APIServer                     # noqa: E402
from tensorhive_tpu.config import Config, HostConfig, set_config    # noqa: E402
from tensorhive_tpu.controllers.nodes import (                      # noqa: E402
    sync_resources_from_infrastructure,
)
from tensorhive_tpu.core.managers.infrastructure import chip_uid    # noqa: E402
from tensorhive_tpu.core.managers.manager import (                  # noqa: E402
    TpuHiveManager,
    set_manager,
)
from tensorhive_tpu.core.nursery import set_ops_factory             # noqa: E402
from tensorhive_tpu.core.transport.fake import (                    # noqa: E402
    FakeCluster,
    FakeOpsFactory,
)
from tensorhive_tpu.db.engine import Engine, set_engine             # noqa: E402
from tensorhive_tpu.db.migrations import ensure_schema              # noqa: E402
from tensorhive_tpu.db.models.reservation import Reservation        # noqa: E402
from tensorhive_tpu.db.models.restriction import Restriction        # noqa: E402
from tensorhive_tpu.db.models.schedule import RestrictionSchedule   # noqa: E402
from tensorhive_tpu.db.models.job import Job                        # noqa: E402
from tensorhive_tpu.db.models.task import SegmentType, Task         # noqa: E402
from tensorhive_tpu.db.models.user import Group, User               # noqa: E402
from tensorhive_tpu.utils.timeutils import utcnow                   # noqa: E402
from datetime import timedelta                                      # noqa: E402

HOSTS = ("vm-0", "vm-1")
CHIPS = 4


def seed_db():
    admin = User(username="admin", email="admin@example.com", password="admin123").save()
    admin.add_role("user"); admin.add_role("admin")
    alice = User(username="alice", email="alice@example.com", password="alice123").save()
    alice.add_role("user")
    group = Group(name="everyone", is_default=True).save()
    group.add_user(admin); group.add_user(alice)

    always = Restriction(name="default: everything", starts_at=utcnow() - timedelta(days=1),
                         ends_at=None, is_global=True).save()
    office = Restriction(name="office hours", starts_at=utcnow() - timedelta(days=1),
                         ends_at=None, is_global=False).save()
    schedule = RestrictionSchedule(schedule_days="12345", hour_start="08:00",
                                   hour_end="20:00").save()
    office.add_schedule(schedule)
    office.apply_to_group(group)

    start = utcnow().replace(minute=0, second=0, microsecond=0) + timedelta(hours=1)
    Reservation(title="flash-attn sweep", description="bench run",
                resource_id=chip_uid("vm-0", 0), user_id=alice.id,
                start=start, end=start + timedelta(hours=3)).save()

    job = Job(name="t2t-base training", description="demo job", user_id=alice.id).save()
    for worker_index, hostname in enumerate(HOSTS):
        task = Task(job_id=job.id, hostname=hostname,
                    command="python3 train.py --preset=t2t-base").save()
        task.add_cmd_segment("TPU_VISIBLE_CHIPS", "0,1,2,3", SegmentType.env_variable)
        task.add_cmd_segment("--process-id", str(worker_index), SegmentType.parameter)
    return always


def telemetry_loop(manager):
    """Drifting fake chip metrics so the dashboard charts move."""
    t0 = time.time()
    while True:
        dt = time.time() - t0
        for host_index, host in enumerate(HOSTS):
            chips = {}
            for index in range(CHIPS):
                phase = host_index * CHIPS + index
                duty = max(0, min(100, 55 + 40 * math.sin(dt / 17 + phase)
                                  + random.uniform(-6, 6)))
                hbm_total = 16384
                hbm_used = int(hbm_total * (0.35 + 0.25 * math.sin(dt / 29 + phase)))
                chips[chip_uid(host, index)] = {
                    "name": f"TPU v5e chip {index}",
                    "index": index,
                    "accelerator_type": "v5litepod-8",
                    "hbm_used_mib": hbm_used,
                    "hbm_total_mib": hbm_total,
                    "hbm_util_pct": round(100 * hbm_used / hbm_total),
                    "duty_cycle_pct": round(duty),
                    "processes": [
                        {"pid": 4242 + phase, "user": "alice",
                         "command": "python3 train.py --preset=t2t-base"},
                    ] if index < 2 and host == "vm-0" else [],
                }
            manager.infrastructure_manager.update_subtree(host, "TPU", chips)
            manager.infrastructure_manager.update_subtree(host, "CPU", {
                f"CPU_{host}": {
                    "util_pct": round(20 + 15 * math.sin(dt / 11 + host_index)),
                    "mem_used_mib": 3200, "mem_total_mib": 16384,
                },
            })
        sync_resources_from_infrastructure(
            manager.infrastructure_manager.infrastructure)
        time.sleep(2)


def main():
    config = Config()
    config.api.secret_key = "demo-secret"
    config.api.url_hostname = "127.0.0.1"
    config.app_server.host = "127.0.0.1"
    for name in HOSTS:
        config.hosts[name] = HostConfig(name=name, backend="local",
                                        accelerator_type="v5litepod-8",
                                        chips=CHIPS)
    set_config(config)

    engine = Engine(":memory:")
    ensure_schema(engine)
    set_engine(engine)

    cluster = FakeCluster()
    for name in HOSTS:
        cluster.add_host(name, chips=CHIPS)
    set_ops_factory(FakeOpsFactory(cluster))

    manager = TpuHiveManager(config=config, services=[])
    set_manager(manager)
    seed_db()

    threading.Thread(target=telemetry_loop, args=(manager,), daemon=True).start()
    api_port = APIServer(config).start()
    app_port = AppServer(config).start()
    print(f"API  : http://127.0.0.1:{api_port}/api")
    print(f"UI   : http://127.0.0.1:{app_port}  (admin/admin123, alice/alice123)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
