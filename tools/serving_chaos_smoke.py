"""Smoke-test the serving data plane's fault tolerance end to end
(``make serving-chaos-smoke``; docs/ROBUSTNESS.md "Serving data plane").

Boots the real daemon surface — WSGI app over a real socket, a live
GenerationService pump, in-memory DB — around an engine wired to a seeded
:class:`ServingFaultPlan`, then proves the resilience contract over HTTP:

1. a healthy streamed ``POST /api/generate`` request is **token-identical**
   to ``decode.generate`` (the baseline the recovery gates compare to);
2. kill a decode step mid-stream: the client's NDJSON stream ends with the
   terminal ``{"error": ...}`` chunk within the request deadline — zero
   hung streams — and the failed request lands in the ledger with
   ``outcome=failed``;
3. the supervisor auto-restores: the engine re-publishes within the
   restart budget and the next request completes **token-identical** to
   the pre-fault baseline;
4. the ``/api/metrics`` scrape carries the restart/failure counters
   (``tpuhive_generate_engine_restarts_total``,
   ``_step_failures_total{kind="fatal"}``,
   ``_requests_total{outcome="failed"}``);
5. a forced crash loop (persistent device-lost) exhausts the restart
   budget: ``POST /api/generate`` answers **503 + Retry-After** with the
   crash-loop reason and the ``engine_crash_loop`` alert FIRES in the
   scrape;
6. clearing the outage + the breaker cooldown recovers: the alert
   RESOLVES, and generation is again token-identical to the baseline;
7. graceful drain over the admin endpoint: admission 503s with
   Retry-After while draining, resume reopens it.

Engines run the f32 tiny config (like the unit suite): token identity is
an exactness statement. Exit 0 = healthy.
"""
from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

SEED = 42
PROMPT = [3, 4, 5, 6, 7, 8, 9, 10]
NEW_TOKENS = 8
DEADLINE_S = 6.0
RESTART_BUDGET = 2
COOLDOWN_S = 0.3

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"serving-chaos-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def request(url: str, body=None, headers=None, method=None):
    """(status, text, headers) over real HTTP; >=400 is a result."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def stream_request(base: str, auth: dict, max_new: int,
                   on_line=None):
    """Stream one generate request line by line (the NDJSON contract);
    returns the parsed lines. ``on_line(index, parsed)`` fires per line —
    the mid-stream kill hook."""
    req = urllib.request.Request(
        f"{base}/generate",
        data=json.dumps({"promptTokens": PROMPT, "maxNewTokens": max_new,
                         "temperature": 0}).encode(),
        headers={"Content-Type": "application/json", **auth})
    lines = []
    with urllib.request.urlopen(req, timeout=60) as resp:
        while True:
            raw = resp.readline()
            if not raw:
                break
            parsed = json.loads(raw)
            lines.append(parsed)
            if on_line is not None:
                on_line(len(lines) - 1, parsed)
    return lines


def wait_for(predicate, timeout_s: float = 10.0, interval_s: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tensorhive_tpu.config import Config, set_config

    config = Config(config_dir=Path("/tmp/tpuhive-serving-chaos-smoke"))
    config.api.secret_key = "serving-chaos-secret"
    config.generation.enabled = True
    config.generation.interval_s = 0.01
    config.generation.default_deadline_s = DEADLINE_S
    config.generation.transient_backoff_s = 0.0
    config.generation.restart_budget = RESTART_BUDGET
    config.generation.restart_window_s = 60.0
    config.generation.restart_cooldown_s = COOLDOWN_S
    config.generation.drain_timeout_s = 5.0
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine as set_db
    from tensorhive_tpu.db.migrations import ensure_schema

    engine_db = Engine(":memory:")
    ensure_schema(engine_db)
    set_db(engine_db)

    from tensorhive_tpu.db.models import User

    admin = User(username="smoke-admin", email="smoke@example.com",
                 password="SuperSecret42").save()
    admin.add_role("user")
    admin.add_role("admin")

    from tensorhive_tpu import serving
    from tensorhive_tpu.core.services.generation import GenerationService
    from tensorhive_tpu.models import decode
    from tensorhive_tpu.models.transformer import PRESETS, TransformerLM
    from tensorhive_tpu.observability.alerts import get_alert_engine
    from tensorhive_tpu.serving.engine import SlotEngine
    from tensorhive_tpu.serving.faults import ServingFaultPlan

    f32_tiny = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32,
                                   use_flash=False, remat=False,
                                   max_seq_len=128)
    params = TransformerLM.init(jax.random.PRNGKey(0), f32_tiny)
    reference = np.asarray(decode.generate(
        params, f32_tiny, jnp.asarray([PROMPT], jnp.int32),
        max_new_tokens=NEW_TOKENS, temperature=0.0))[0, len(PROMPT):].tolist()

    plan = ServingFaultPlan(seed=SEED)
    print(f"serving-chaos-smoke: seed={SEED}")

    def factory():
        engine = SlotEngine(params, f32_tiny, slots=2, max_len=96,
                            queue_depth=4, kv_quant="off",
                            default_deadline_s=DEADLINE_S,
                            fault_plan=plan)
        engine.warmup(prompt_lens=(len(PROMPT),))
        return engine

    generation = GenerationService(config=config, engine=factory(),
                                   engine_factory=factory)
    generation.start()

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    try:
        status, body, _ = request(f"{base}/user/login", body={
            "username": "smoke-admin", "password": "SuperSecret42"})
        check(status == 200, f"admin login over HTTP (got {status})")
        auth = {"Authorization": "Bearer " + json.loads(body)["accessToken"]}

        def engine_published():
            return serving.get_engine() is not None

        # -- 1: healthy baseline, token-identical to decode.generate -------
        lines = stream_request(base, auth, NEW_TOKENS)
        done = lines[-1]
        check(done.get("outcome") == "completed",
              f"baseline stream completed ({done})")
        check(done.get("tokens") == reference,
              "baseline tokens identical to decode.generate "
              f"({done.get('tokens')} vs {reference})")

        # -- 2: kill a step mid-stream: terminal error chunk, no hang ------
        kill_state = {"armed_at": None}

        def kill_mid_stream(index, parsed):
            if index == 1 and "token" in parsed:
                plan.fail_next("step", 1)
                kill_state["armed_at"] = time.monotonic()

        lines = stream_request(base, auth, max_new=24,
                               on_line=kill_mid_stream)
        terminal_s = time.monotonic() - kill_state["armed_at"]
        killed = lines[-1]
        check("error" in killed,
              f"mid-stream kill ended with a terminal error chunk "
              f"({killed})")
        check(terminal_s < DEADLINE_S,
              f"terminal chunk within the deadline "
              f"({terminal_s:.3f}s < {DEADLINE_S:g}s — zero hung streams)")
        check(sum(1 for line in lines if "token" in line) >= 2,
              "tokens streamed before the injected fault")

        status, body, _ = request(
            f"{base}/admin/requests?outcome=failed", headers=auth)
        check(status == 200 and len(json.loads(body)["requests"]) >= 1,
              "killed request ledgered with outcome=failed")

        # -- 3: auto-restore; next request token-identical -----------------
        check(wait_for(engine_published, timeout_s=10.0),
              "engine auto-restored within the budget")
        lines = stream_request(base, auth, NEW_TOKENS)
        check(lines[-1].get("tokens") == reference,
              "post-restore tokens identical to decode.generate")

        # -- 4: restart/failure counters in the scrape ---------------------
        status, scrape, _ = request(f"{base}/metrics")
        check(status == 200, f"GET /metrics (got {status})")

        def counter_at_least(name, minimum):
            for line in scrape.splitlines():
                if line.startswith(name) and not line.startswith("#"):
                    if float(line.rsplit(" ", 1)[1]) >= minimum:
                        return True
            return False

        check(counter_at_least(
            "tpuhive_generate_engine_restarts_total", 1),
            "engine_restarts_total >= 1 in the scrape")
        check(counter_at_least(
            'tpuhive_generate_step_failures_total{kind="fatal"}', 1),
            'step_failures_total{kind="fatal"} >= 1 in the scrape')
        check(counter_at_least(
            'tpuhive_generate_requests_total{outcome="failed"}', 1),
            'requests_total{outcome="failed"} >= 1 in the scrape')

        # -- 5: forced crash loop trips the breaker ------------------------
        plan.set_device_lost(True)
        for attempt in range(RESTART_BUDGET + 1):
            if not wait_for(engine_published, timeout_s=5.0):
                break
            lines = stream_request(base, auth, max_new=4)
            check("error" in lines[-1],
                  f"crash-loop round {attempt}: stream ended terminally")
        check(wait_for(
            lambda: serving.get_serving_state()["crash_loop"],
            timeout_s=5.0), "crash-loop breaker tripped")
        status, body, headers = request(f"{base}/generate", body={
            "promptTokens": PROMPT, "maxNewTokens": 4, "temperature": 0},
            headers=auth)
        check(status == 503, f"crash loop answers 503 (got {status})")
        check("crash loop" in json.loads(body).get("msg", ""),
              "503 body names the crash loop")
        check(int(headers.get("Retry-After", 0)) >= 1,
              "503 carries an honest Retry-After")
        get_alert_engine().evaluate()
        status, scrape, _ = request(f"{base}/metrics")
        check('tpuhive_alerts_firing{rule="engine_crash_loop"'
              in scrape.replace("severity=\"critical\",", "")
              or 'rule="engine_crash_loop"' in scrape,
              "engine_crash_loop gauge exported")
        firing = [line for line in scrape.splitlines()
                  if 'rule="engine_crash_loop"' in line]
        check(any(line.endswith(" 1") or line.endswith(" 1.0")
                  for line in firing),
              f"engine_crash_loop FIRING in the scrape ({firing})")

        # -- 6: recovery resolves the loop ---------------------------------
        plan.set_device_lost(False)
        time.sleep(COOLDOWN_S + 0.05)
        check(wait_for(engine_published, timeout_s=10.0),
              "engine recovered after the cooldown probe")
        get_alert_engine().evaluate()
        status, scrape, _ = request(f"{base}/metrics")
        firing = [line for line in scrape.splitlines()
                  if 'rule="engine_crash_loop"' in line]
        check(any(line.endswith(" 0") or line.endswith(" 0.0")
                  for line in firing),
              f"engine_crash_loop RESOLVED in the scrape ({firing})")
        lines = stream_request(base, auth, NEW_TOKENS)
        check(lines[-1].get("tokens") == reference,
              "post-recovery tokens identical to decode.generate")

        # -- 7: graceful drain over the admin endpoint ---------------------
        status, body, _ = request(f"{base}/admin/generate/drain",
                                  body={}, headers=auth)
        check(status == 200 and json.loads(body)["draining"] is True,
              f"drain accepted (got {status})")
        status, body, headers = request(f"{base}/generate", body={
            "promptTokens": PROMPT, "maxNewTokens": 4, "temperature": 0},
            headers=auth)
        check(status == 503 and "draining" in json.loads(body)["msg"],
              f"draining answers 503 with the reason (got {status})")
        check(int(headers.get("Retry-After", 0)) >= 1,
              "draining 503 carries Retry-After")
        status, body, _ = request(f"{base}/admin/generate/resume",
                                  body={}, headers=auth)
        check(status == 200 and json.loads(body)["draining"] is False,
              f"resume accepted (got {status})")
        lines = stream_request(base, auth, NEW_TOKENS)
        check(lines[-1].get("outcome") == "completed",
              "admission reopened after resume")
    finally:
        server.stop()
        generation.shutdown()
        generation.join(timeout=10)

    # -- 8 (TPUHIVE_LOCK_WITNESS=1 only): the whole run doubled as a lock
    # witness — zero observed ABBA inversions, and every observed order
    # edge must exist in the static TH-LOCK graph (the model's soundness
    # proof; docs/STATIC_ANALYSIS.md "TH-LOCK")
    from tensorhive_tpu.utils import lockwitness

    if lockwitness.witness_enabled():
        dump_path = Path("/tmp/tpuhive-serving-chaos-witness.json")
        snap = lockwitness.dump(str(dump_path))
        check(snap["locks"], "witness observed named locks "
              f"({len(snap['locks'])} names, {len(snap['edges'])} edges)")
        check(not snap["inversions"],
              f"zero runtime lock inversions ({snap['inversions']})")
        from tools.analysis.rules.locks import compare_witness

        ok, lines = compare_witness(
            dump_path, Path(__file__).resolve().parent.parent)
        for line in lines:
            print(f"serving-chaos-smoke: {line}")
        check(ok, "observed lock-order edges ⊆ static TH-LOCK graph")

    if PROBLEMS:
        print(f"serving-chaos-smoke: {len(PROBLEMS)} problem(s)",
              file=sys.stderr)
        return 1
    print("serving-chaos-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
