"""Perf lab: measure train_loop step time under controlled variants.

Usage: python tools/perf_lab.py VARIANT [preset] [batch] [seq] [steps]

Variants:
  baseline          — the code as committed
  noattn            — attention replaced by identity (v passthrough): lower
                      bound on step time with zero attention cost
  dense             — XLA reference attention instead of pallas kernels
  blocks:BQ:BK      — override flash block sizes
  noremat/remat     — force remat off/on

All timings via tensorhive_tpu.train.train_loop (the only trustworthy
timing path on the tunneled chip — kernel micros are garbage there).
"""
from __future__ import annotations

import dataclasses
import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the axon TPU plugin overrides the env var; the config API sticks —
    # same guard as __graft_entry__._ensure_cpu_devices_if_requested, so
    # sweep subprocesses can run off-TPU (CI smoke) instead of hanging on
    # a dead tunnel
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass


def main() -> None:
    variant = sys.argv[1] if len(sys.argv) > 1 else "baseline"
    preset = sys.argv[2] if len(sys.argv) > 2 else "t2t-base"
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    seq = int(sys.argv[4]) if len(sys.argv) > 4 else 1024
    steps = int(sys.argv[5]) if len(sys.argv) > 5 else 24

    import tensorhive_tpu.models.transformer as T
    from tensorhive_tpu.models.transformer import PRESETS, train_flops_per_token
    import importlib

    # ops/__init__ re-exports the flash_attention FUNCTION, shadowing the
    # submodule attribute — go through sys.modules for the module itself
    FA = importlib.import_module("tensorhive_tpu.ops.flash_attention")
    from tensorhive_tpu.train import TrainConfig, train_loop

    remat = False
    if variant == "noattn":
        T.flash_attention = lambda q, k, v, causal=True: v
    elif variant == "dense":
        mc0 = PRESETS[preset]
        T.flash_attention = functools.partial(FA.reference_attention)
    elif variant.startswith("blocks:"):
        _, bq, bk = variant.split(":")
        T.flash_attention = functools.partial(
            FA.flash_attention, block_q=int(bq), block_k=int(bk))
    elif variant.startswith("streaming:"):
        # force the 3D streaming kernels (BlockSpec-pipelined K/V) instead
        # of the resident fori_loop kernels
        _, bq, bk = variant.split(":")
        FA.RESIDENT_KV_MAX_BYTES = 0
        T.flash_attention = functools.partial(
            FA.flash_attention, block_q=int(bq), block_k=int(bk))
    elif variant == "jaxflash":
        # canonical jax pallas TPU flash kernel as a comparison point:
        # isolates "our kernels are slow" from "pallas-on-this-chip is slow"
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash)

        def call(q, k, v, causal=True):
            # jax kernel wants [B, H, S, D]
            out = jax_flash(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                            v.transpose(0, 2, 1, 3), causal=causal)
            return out.transpose(0, 2, 1, 3)

        T.flash_attention = call
    elif variant == "pallascopy":
        # trivial pallas kernel as attention: isolates fixed per-custom-call
        # cost from kernel compute (2 calls/layer: fwd copy + bwd copy)
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _copy_kernel(v_ref, o_ref):
            o_ref[...] = v_ref[...]

        def _pallas_copy(x):
            bh = x.shape[0]
            return pl.pallas_call(
                _copy_kernel,
                grid=(bh,),
                in_specs=[pl.BlockSpec((1,) + x.shape[1:], lambda b: (b, 0, 0))],
                out_specs=pl.BlockSpec((1,) + x.shape[1:], lambda b: (b, 0, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)

        @jax.custom_vjp
        def copy_attn(q, k, v):
            b, s, h, d = v.shape
            return _pallas_copy(v.reshape(b * h, s, d).copy()
                                if False else
                                v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
                                ).reshape(b, h, s, d).transpose(0, 2, 1, 3)

        def copy_fwd(q, k, v):
            return copy_attn(q, k, v), None

        def copy_bwd(_, g):
            b, s, h, d = g.shape
            gc = _pallas_copy(g.transpose(0, 2, 1, 3).reshape(b * h, s, d))
            gc = gc.reshape(b, h, s, d).transpose(0, 2, 1, 3)
            return (jnp.zeros_like(gc), jnp.zeros_like(gc), gc)

        copy_attn.defvjp(copy_fwd, copy_bwd)
        T.flash_attention = lambda q, k, v, causal=True: copy_attn(q, k, v)
    elif variant == "nomlp":
        # MLP half → identity: step time drop = the MLP's share
        T.TransformerLM.block_mlp_half = staticmethod(
            lambda x, block, config: x)
    elif variant == "nohead":
        # LM head + CE replaced by a trivial trunk loss: the drop = the
        # head matmul + softmax-CE share (fwd+bwd)
        import jax.numpy as jnp

        def loss_no_head(params, tokens, config, mesh=None):
            x = T.TransformerLM.apply_trunk(params, tokens[:, :-1], config,
                                            mesh=mesh)
            return jnp.mean(jnp.square(x.astype(jnp.float32)))

        T.TransformerLM.loss = staticmethod(loss_no_head)
    elif variant.startswith("bhblock:"):
        # experimental G-heads-per-program resident forward
        # (ops/flash_attention.py _fwd_kernel_resident_bh)
        os.environ["TPUHIVE_FLASH_BH_BLOCK"] = variant.split(":")[1]
    elif variant.startswith("gqa:"):
        # grouped-query attention point: n_kv_heads < n_heads through the
        # native-GQA kernels (no expanded K/V copy)
        kv_heads = int(variant.split(":")[1])
    elif variant == "remat":
        remat = True
    elif variant == "remat-mlp":
        remat = "mlp"
    elif variant != "baseline" and variant != "noremat":
        raise SystemExit(f"unknown variant {variant}")

    model_config = dataclasses.replace(
        PRESETS[preset], remat=bool(remat),
        remat_policy="mlp" if remat == "mlp" else "block")
    if variant.startswith("gqa:"):
        model_config = dataclasses.replace(model_config, n_kv_heads=kv_heads)
    train_config = TrainConfig(batch_size=batch, seq_len=seq,
                               warmup_steps=2, total_steps=100)
    metrics = train_loop(model_config, train_config, mesh=None,
                         num_steps=steps, log_every=0,
                         sync_every=max(1, steps // 3))
    step_ms = metrics["step_time_s"] * 1e3
    toks = batch * seq * metrics["steps_per_sec"]
    flops_per_token = train_flops_per_token(model_config, seq, remat=False)
    mfu = toks * flops_per_token / (197.0e12)
    print(f"{variant} {preset} b{batch} s{seq} remat={remat}: "
          f"{step_ms:.2f} ms/step, {toks:,.0f} tok/s, mfu={mfu:.4f}, "
          f"loss={metrics['loss']:.4f}")


#: the component-share ablation set PERF.md's step-share table is built
#: from; each entry is (variant, preset, batch, seq)
SWEEP = [
    ("baseline", "t2t-base", 64, 1024),
    ("noattn", "t2t-base", 64, 1024),
    ("nomlp", "t2t-base", 64, 1024),
    ("nohead", "t2t-base", 64, 1024),
    ("dense", "t2t-base", 64, 1024),
    ("baseline", "t2t-big", 32, 1024),
    ("noattn", "t2t-big", 32, 1024),
    ("nomlp", "t2t-big", 32, 1024),
    ("nohead", "t2t-big", 32, 1024),
]


def sweep(out_path: str) -> None:
    """Run the ablation set, each variant in its OWN subprocess with a hard
    timeout — the r4 attempt died to one compile hanging 10+ minutes on a
    sick tunnel; a sweep must record every variant that completes and mark
    the ones that don't. Writes a JSON artifact for docs/bench_runs/."""
    import json
    import re
    import subprocess
    import time

    # anchor relative paths to the repo root and fail BEFORE the (up to
    # 90-minute) sweep if the artifact cannot be written
    if not os.path.isabs(out_path):
        out_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            out_path)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "a"):
        pass

    results = []
    for variant, preset, batch, seq in SWEEP:
        argv = [sys.executable, os.path.abspath(__file__), variant, preset,
                str(batch), str(seq), "24"]
        started = time.perf_counter()
        try:
            proc = subprocess.run(argv, capture_output=True, text=True,
                                  timeout=600)
            line = (proc.stdout.strip().splitlines() or [""])[-1]
            match = re.search(
                r"([\d.]+) ms/step, ([\d,]+) tok/s, mfu=([\d.]+)", line)
            entry = {"variant": variant, "preset": preset, "batch": batch,
                     "seq": seq, "rc": proc.returncode,
                     "elapsed_s": round(time.perf_counter() - started, 1)}
            if proc.returncode == 0 and match:
                entry.update(step_ms=float(match.group(1)),
                             tokens_per_sec=float(match.group(2).replace(",", "")),
                             mfu=float(match.group(3)))
            else:
                entry["error"] = (proc.stderr.strip()[-300:]
                                  or "no parsable output")
        except subprocess.TimeoutExpired:
            entry = {"variant": variant, "preset": preset, "batch": batch,
                     "seq": seq, "rc": None, "error": "timeout after 600s"}
        print(f"sweep: {entry}", file=sys.stderr, flush=True)
        results.append(entry)
    doc = {"purpose": "component step-share ablation (PERF.md table)",
           "method": "train_loop wall-clock, per-variant subprocess, "
                     "600s timeout each",
           "results": results}
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "sweep":
        sweep(sys.argv[2] if len(sys.argv) > 2
              else "docs/bench_runs/r5_ablation.json")
    else:
        main()
