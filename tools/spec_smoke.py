"""Smoke-test the speculative decoding lane end to end (``make spec-smoke``;
docs/SERVING.md "Speculative decoding").

Boots the real daemon surface — WSGI app over a real socket, a live
GenerationService pump, in-memory DB — around a SPECULATIVE engine, then
proves the lane's operational contract over HTTP:

1. stream one authenticated ``POST /api/generate`` request through the
   spec-on engine and record its tokens;
2. the request's ledger row must carry the acceptance fields
   (``draftTokens`` ≥ one tick of proposals, ``acceptedTokens``/
   ``acceptanceRate`` present), and ``/api/generate/stats`` must report
   the lane on with its window depth;
3. the ``/api/metrics`` scrape must export the
   ``tpuhive_generate_spec_{proposed,accepted}_total`` counters;
4. ZERO post-warmup recompiles across the speculative ticks (verify,
   draft-propose and prefill executables all fingerprint-stable);
5. swap in a ``speculative="off"`` engine built from the SAME params and
   stream the SAME prompt: the two streams must be **token-identical** —
   the hard gate that makes speculation a pure latency trade, never a
   behavior change.

Engines run the f32 tiny config (like the unit suite): the identity gate
is an exactness statement, and bf16 batched-vs-sequential accumulation
can flip greedy near-ties on untrained weights (the PR 3 caveat).

Exit 0 = healthy.
"""
from __future__ import annotations

import dataclasses
import json
import os
import sys
import urllib.error
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

PROMPT = [3, 4, 5, 6, 7, 8, 9, 10]
NEW_TOKENS = 8
SPEC_TOKENS = 4

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"spec-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def request(url: str, body=None, headers=None, method=None):
    """(status, text, headers) over real HTTP; >=400 is a result."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def stream_tokens(base: str, auth: dict):
    status, body, headers = request(f"{base}/generate", body={
        "promptTokens": PROMPT, "maxNewTokens": NEW_TOKENS,
        "temperature": 0}, headers=auth)
    check(status == 200, f"POST /generate streamed (got {status})")
    lines = [json.loads(line) for line in body.strip().splitlines()]
    done = lines[-1]
    check(done.get("outcome") == "completed",
          f"stream completed (got {done})")
    return done.get("tokens"), headers.get("X-Request-Id")


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tensorhive_tpu.config import Config, set_config

    config = Config(config_dir=Path("/tmp/tpuhive-spec-smoke"))
    config.api.secret_key = "spec-smoke-secret"
    config.generation.enabled = True
    config.generation.interval_s = 0.01
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine as set_db
    from tensorhive_tpu.db.migrations import ensure_schema

    engine_db = Engine(":memory:")
    ensure_schema(engine_db)
    set_db(engine_db)

    from tensorhive_tpu.db.models import User

    admin = User(username="smoke-admin", email="smoke@example.com",
                 password="SuperSecret42").save()
    admin.add_role("user")
    admin.add_role("admin")

    from tensorhive_tpu.models.transformer import PRESETS, TransformerLM
    from tensorhive_tpu.core.services.generation import GenerationService
    from tensorhive_tpu.serving.engine import SlotEngine

    f32_tiny = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32,
                                   use_flash=False, remat=False,
                                   max_seq_len=128)
    params = TransformerLM.init(jax.random.PRNGKey(0), f32_tiny)

    def build(speculative: str) -> SlotEngine:
        engine = SlotEngine(params, f32_tiny, slots=2, max_len=96,
                            queue_depth=4, speculative=speculative,
                            kv_quant="off", spec_tokens=SPEC_TOKENS)
        engine.warmup(prompt_lens=(len(PROMPT),))
        return engine

    spec_engine = build("on")
    check(spec_engine.stats()["speculative"] == "on",
          "speculative engine resolved on")
    step_execs = spec_engine.step_executable._cache_size()
    draft_execs = spec_engine.spec_draft_executable._cache_size()
    prefill_execs = spec_engine.prefill_executable._cache_size()

    generation = GenerationService(config=config, engine=spec_engine)
    generation.start()

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    off_service = None
    try:
        status, body, _ = request(f"{base}/user/login", body={
            "username": "smoke-admin", "password": "SuperSecret42"})
        check(status == 200, f"admin login over HTTP (got {status})")
        auth = {"Authorization": "Bearer " + json.loads(body)["accessToken"]}

        # -- 1: spec-on stream ---------------------------------------------
        spec_tokens_out, request_id = stream_tokens(base, auth)
        check(bool(request_id), "X-Request-Id header present")
        check(isinstance(spec_tokens_out, list)
              and len(spec_tokens_out) == NEW_TOKENS,
              f"spec-on stream emitted {NEW_TOKENS} tokens")

        # -- 2: ledger row carries acceptance fields; stats show the lane --
        status, body, _ = request(f"{base}/admin/requests", headers=auth)
        check(status == 200, f"GET /admin/requests (got {status})")
        rows = [row for row in json.loads(body)["requests"]
                if row["requestId"] == request_id]
        check(len(rows) == 1, "exactly one ledger row for the request")
        if rows:
            row = rows[0]
            check((row["draftTokens"] or 0) >= SPEC_TOKENS,
                  f"ledger draftTokens >= one tick of proposals ({row})")
            check(row["acceptedTokens"] is not None
                  and "acceptanceRate" in row,
                  "ledger carries acceptedTokens/acceptanceRate")
        status, body, _ = request(f"{base}/generate/stats", headers=auth)
        check(status == 200, f"GET /generate/stats (got {status})")
        stats = json.loads(body)
        check(stats["speculative"] == "on"
              and stats["specTokens"] == SPEC_TOKENS,
              f"stats report the lane on at depth {SPEC_TOKENS}")
        check(stats["specProposed"] >= SPEC_TOKENS,
              f"stats count proposals ({stats['specProposed']})")

        # -- 3: acceptance counters in the scrape --------------------------
        status, scrape, _ = request(f"{base}/metrics")
        check(status == 200, f"GET /metrics (got {status})")
        check("tpuhive_generate_spec_proposed_total" in scrape,
              "spec proposed counter in the exposition")
        check("tpuhive_generate_spec_accepted_total" in scrape,
              "spec accepted counter in the exposition")

        # -- 4: zero post-warmup recompiles across speculative ticks -------
        check(spec_engine.step_executable._cache_size() == step_execs
              and spec_engine.spec_draft_executable._cache_size()
              == draft_execs
              and spec_engine.prefill_executable._cache_size()
              == prefill_execs,
              "zero new executables while the speculative request ran")

        # -- 5: spec-off stream must be token-identical --------------------
        generation.shutdown()
        generation.join(timeout=5)
        off_engine = build("off")
        off_service = GenerationService(config=config, engine=off_engine)
        off_service.start()
        off_tokens_out, _ = stream_tokens(base, auth)
        check(off_tokens_out == spec_tokens_out,
              "spec-on stream token-identical to spec-off stream "
              f"({spec_tokens_out} vs {off_tokens_out})")
    finally:
        server.stop()
        generation.shutdown()
        generation.join(timeout=5)
        if off_service is not None:
            off_service.shutdown()
            off_service.join(timeout=5)

    if PROBLEMS:
        print(f"spec-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("spec-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
