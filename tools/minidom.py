"""Browser environment for tools/minijs.py: DOM tree, selectors, fetch.

Enough of the DOM for the in-repo UI (innerHTML parse/serialize,
getElementById/querySelectorAll with the selector subset the UI uses,
input value/checked, dataset, dialogs, event listeners + inline on*
handlers) plus localStorage, location and a fetch() whose transport is a
python callback — the UI tests plug in werkzeug's test client so UI flows
hit the REAL WSGI app. Strict like the interpreter: unsupported selectors
or DOM APIs raise instead of pretending.
"""
from __future__ import annotations

import re
from html.parser import HTMLParser
from typing import Any, Callable, Dict, List, Optional

from tools.minijs import (
    UNDEFINED,
    Interpreter,
    JSArray,
    JSError,
    JSException,
    JSObject,
    js_str,
    js_truthy,
)

VOID_TAGS = {"br", "hr", "img", "input", "meta", "link"}


class Node:
    def __init__(self, tag: str, attrs: Optional[Dict[str, str]] = None):
        self.tag = tag.lower()
        self.attrs: Dict[str, str] = dict(attrs or {})
        self.children: List["Node"] = []
        self.text_parts: List[Any] = []   # interleaved str | Node (in order)
        self.parent: Optional["Node"] = None
        self.listeners: Dict[str, List[Any]] = {}
        self.expando: Dict[str, Any] = {}  # el._t and friends
        self.value_override: Optional[str] = None
        self.checked_override: Optional[bool] = None
        self.dialog_open = False

    # -- tree ---------------------------------------------------------------
    def append(self, child):
        child.parent = self
        self.children.append(child)
        self.text_parts.append(child)

    def remove_child(self, child):
        child.parent = None
        self.children = [c for c in self.children if c is not child]
        self.text_parts = [p for p in self.text_parts if p is not child]

    def walk(self):
        for child in self.children:
            yield child
            yield from child.walk()

    # -- text / html --------------------------------------------------------
    @property
    def text_content(self) -> str:
        out = []
        for part in self.text_parts:
            out.append(part.text_content if isinstance(part, Node) else part)
        return "".join(out)

    def set_text(self, text: str):
        self.children = []
        self.text_parts = [text]

    def inner_html(self) -> str:
        out = []
        for part in self.text_parts:
            out.append(part.outer_html() if isinstance(part, Node) else part)
        return "".join(out)

    def outer_html(self) -> str:
        attrs = "".join(f' {k}="{v}"' for k, v in self.attrs.items())
        if self.tag in VOID_TAGS:
            return f"<{self.tag}{attrs}>"
        return f"<{self.tag}{attrs}>{self.inner_html()}</{self.tag}>"

    def set_inner_html(self, html: str):
        self.children = []
        self.text_parts = []
        _parse_into(self, html)

    # -- classes / matching ---------------------------------------------------
    @property
    def class_list(self) -> List[str]:
        return self.attrs.get("class", "").split()

    def matches(self, compound: "_Compound") -> bool:
        if compound.tag and self.tag != compound.tag:
            return False
        if compound.id and self.attrs.get("id") != compound.id:
            return False
        for cls in compound.classes:
            if cls not in self.class_list:
                return False
        for pseudo in compound.pseudos:
            if pseudo == "checked":
                if not self.checked:
                    return False
            else:
                raise JSError(f"unsupported pseudo-class :{pseudo}")
        for name, expected in compound.attr_tests:
            if name == "open" and self.tag == "dialog":
                actual = "" if self.dialog_open else None
            else:
                actual = self.attrs.get(name)
            if actual is None:
                return False
            if expected is not None and actual != expected:
                return False
        return True

    # -- form state -----------------------------------------------------------
    @property
    def value(self) -> str:
        if self.value_override is not None:
            return self.value_override
        if self.tag == "textarea":
            return self.text_content
        if self.tag == "select":
            options = [n for n in self.walk() if n.tag == "option"]
            for option in options:
                if "selected" in option.attrs:
                    return option.attrs.get("value", option.text_content.strip())
            if options:
                return options[0].attrs.get("value",
                                            options[0].text_content.strip())
            return ""
        return self.attrs.get("value", "")

    @value.setter
    def value(self, text: str):
        self.value_override = text

    @property
    def checked(self) -> bool:
        if self.checked_override is not None:
            return self.checked_override
        return "checked" in self.attrs

    def closest(self, selector_text: str):
        chain = _parse_selector(selector_text)
        node = self
        while node is not None:
            if _matches_chain(node, chain):
                return node
            node = node.parent
        return None

    def __repr__(self):
        ident = f"#{self.attrs['id']}" if "id" in self.attrs else ""
        return f"<{self.tag}{ident}>"


class _Builder(HTMLParser):
    def __init__(self, root: Node):
        super().__init__(convert_charrefs=True)
        self.stack = [root]

    def handle_starttag(self, tag, attrs):
        node = Node(tag, {k: (v if v is not None else "") for k, v in attrs})
        self.stack[-1].append(node)
        if tag.lower() not in VOID_TAGS:
            self.stack.append(node)

    def handle_endtag(self, tag):
        for index in range(len(self.stack) - 1, 0, -1):
            if self.stack[index].tag == tag.lower():
                del self.stack[index:]
                return

    def handle_data(self, data):
        top = self.stack[-1]
        top.text_parts.append(data)


def _parse_into(root: Node, html: str):
    builder = _Builder(root)
    builder.feed(html)
    builder.close()


# -- selectors ---------------------------------------------------------------

_ATTR_RE = r'\[([\w-]+)(?:="([^"]*)")?\]'


class _Compound:
    def __init__(self, text: str):
        self.tag = ""
        self.id = ""
        self.classes: List[str] = []
        self.pseudos: List[str] = []
        #: [(name, value|None)] — value None = presence test ([open])
        self.attr_tests: List[tuple] = []
        for name, value in re.findall(_ATTR_RE, text):
            self.attr_tests.append((name, value if value != "" else None))
        text = re.sub(_ATTR_RE, "", text)
        for kind, name in re.findall(r"([.#:]?)([\w-]+)", text):
            if kind == ".":
                self.classes.append(name)
            elif kind == "#":
                self.id = name
            elif kind == ":":
                self.pseudos.append(name)
            else:
                self.tag = name.lower()
        stripped = re.sub(r"([.#:]?)([\w-]+)", "", text).strip()
        if stripped:
            raise JSError(f"unsupported selector piece {text!r}")


def _parse_selector(text: str) -> List[List[_Compound]]:
    """selector list → [chain]; chain = [compound, ...] (descendant only)."""
    chains = []
    for alternative in text.split(","):
        alternative = alternative.strip()
        if not alternative:
            continue
        if ">" in alternative or "+" in alternative or "~" in alternative:
            raise JSError(f"unsupported selector {alternative!r}")
        chains.append([_Compound(part) for part in alternative.split()])
    return chains


def _matches_chain(node: Node, chains) -> bool:
    for chain in chains:
        if not node.matches(chain[-1]):
            continue
        current, remaining = node.parent, list(chain[:-1])
        while remaining and current is not None:
            if current.matches(remaining[-1]):
                remaining.pop()
            current = current.parent
        if not remaining:
            return True
    return False


def query_all(root: Node, selector_text: str) -> List[Node]:
    chains = _parse_selector(selector_text)
    return [node for node in root.walk() if _matches_chain(node, chains)]


# ---------------------------------------------------------------------------
# JS-visible wrappers
# ---------------------------------------------------------------------------


class Element:
    """js_get/js_set protocol adapter over a Node. One Element per Node
    (stored on the node) so JS identity checks like `calDrag.col !== col`
    behave across repeated querySelectorAll calls."""

    def __new__(cls, node: Node, page: "Page"):
        cached = getattr(node, "_element", None)
        if cached is not None:
            return cached
        element = super().__new__(cls)
        node._element = element
        return element

    def __init__(self, node: Node, page: "Page"):
        self.node = node
        self.page = page

    # -- interpreter protocol -------------------------------------------------
    def js_get(self, prop):
        node, page = self.node, self.page
        wrap = page.wrap
        simple = {
            "innerHTML": lambda: node.inner_html(),
            "outerHTML": lambda: node.outer_html(),
            "textContent": lambda: node.text_content,
            "tagName": lambda: node.tag.upper(),
            "id": lambda: node.attrs.get("id", ""),
            "className": lambda: node.attrs.get("class", ""),
            "value": lambda: node.value,
            "checked": lambda: node.checked,
            "parentElement": lambda: wrap(node.parent) if node.parent else None,
            "children": lambda: JSArray([wrap(c) for c in node.children]),
            "open": lambda: node.dialog_open,
        }
        if prop in simple:
            return simple[prop]()
        if prop == "style":
            return _StyleProxy(node)
        if prop == "dataset":
            return _DatasetProxy(node)
        if prop == "classList":
            return _class_list_api(node)
        methods = {
            "getElementById": lambda ident="": page.by_id(js_str(ident)),
            "querySelector": lambda sel="": (
                [wrap(n) for n in query_all(node, js_str(sel))[:1]] or [None])[0],
            "querySelectorAll": lambda sel="": JSArray(
                [wrap(n) for n in query_all(node, js_str(sel))]),
            "addEventListener": lambda kind="", fn=None, *_:
                node.listeners.setdefault(js_str(kind), []).append(fn),
            "removeEventListener": lambda kind="", fn=None, *_:
                node.listeners.get(js_str(kind), []) and
                node.listeners[js_str(kind)].remove(fn),
            "appendChild": lambda child=None: (node.append(child.node),
                                               child)[1],
            "remove": lambda: node.parent and node.parent.remove_child(node),
            "closest": lambda sel="": wrap(node.closest(js_str(sel))),
            "getBoundingClientRect": lambda: JSObject(
                {"top": 0.0, "left": 0.0, "bottom": 1056.0, "right": 200.0,
                 "width": 200.0, "height": 1056.0}),
            "getAttribute": lambda name="": node.attrs.get(js_str(name), None),
            "setAttribute": lambda name="", value="":
                node.attrs.__setitem__(js_str(name), js_str(value)),
            "showModal": lambda: setattr(node, "dialog_open", True),
            "close": lambda: setattr(node, "dialog_open", False),
            "focus": lambda: UNDEFINED,
            "click": lambda: page.fire(self, "click"),
            "dispatchEvent": lambda event=None: page.dispatch(self, event),
            "contains": lambda other=None: other is not None and (
                other.node is node or any(c is other.node for c in node.walk())),
        }
        if prop in methods:
            return _as_native(methods[prop])
        if prop in node.expando:
            return node.expando[prop]
        if prop.startswith("on") or prop in ("_t",):
            return node.expando.get(prop, UNDEFINED)
        return UNDEFINED

    def js_set(self, prop, value):
        node = self.node
        if prop == "innerHTML":
            node.set_inner_html(js_str(value))
            return
        if prop == "textContent":
            node.set_text(js_str(value))
            return
        if prop == "value":
            node.value = js_str(value)
            return
        if prop == "checked":
            node.checked_override = js_truthy(value)
            return
        if prop == "className":
            node.attrs["class"] = js_str(value)
            return
        if prop == "id":
            node.attrs["id"] = js_str(value)
            return
        node.expando[prop] = value

    def js_delete(self, prop):
        self.node.expando.pop(prop, None)

    def __repr__(self):
        return repr(self.node)


class _StyleProxy:
    def __init__(self, node: Node):
        self.node = node

    def _styles(self) -> Dict[str, str]:
        out = {}
        for piece in self.node.attrs.get("style", "").split(";"):
            if ":" in piece:
                key, _, value = piece.partition(":")
                out[key.strip()] = value.strip()
        return out

    def js_get(self, prop):
        return self._styles().get(_css_name(prop), "")

    def js_set(self, prop, value):
        styles = self._styles()
        styles[_css_name(prop)] = js_str(value)
        self.node.attrs["style"] = ";".join(f"{k}:{v}" for k, v in styles.items())


def _css_name(prop: str) -> str:
    return re.sub(r"([A-Z])", lambda m: "-" + m.group(1).lower(), prop)


class _DatasetProxy:
    def __init__(self, node: Node):
        self.node = node

    def js_get(self, prop):
        value = self.node.attrs.get("data-" + _css_name(prop))
        return value if value is not None else UNDEFINED

    def js_set(self, prop, value):
        self.node.attrs["data-" + _css_name(prop)] = js_str(value)

    def js_delete(self, prop):
        self.node.attrs.pop("data-" + _css_name(prop), None)


def _class_list_api(node: Node):
    def mutate(fn):
        def runner(name=""):
            classes = node.class_list
            fn(classes, js_str(name))
            node.attrs["class"] = " ".join(classes)
        return _as_native(runner)

    return JSObject({
        "add": mutate(lambda cl, n: cl.append(n) if n not in cl else None),
        "remove": mutate(lambda cl, n: cl.remove(n) if n in cl else None),
        "toggle": mutate(lambda cl, n: cl.remove(n) if n in cl else cl.append(n)),
        "contains": _as_native(lambda name="": js_str(name) in node.class_list),
    })


def _as_native(fn):
    fn._js_native = True

    def wrapper(*args):
        result = fn(*args)
        if result is None:
            return UNDEFINED
        if isinstance(result, bool):
            return result
        if isinstance(result, int):
            return float(result)
        return result
    wrapper._js_native = True
    return wrapper


# ---------------------------------------------------------------------------
# page: document + window plumbing
# ---------------------------------------------------------------------------


class Page:
    """One loaded page: DOM root + document/window globals wired into an
    Interpreter. `transport(method, url, headers, body) -> (status, json)`
    backs fetch()."""

    def __init__(self, interp: Interpreter,
                 transport: Callable[[str, str, Dict[str, str], Optional[str]],
                                     Any],
                 hostname: str = "testhost"):
        self.interp = interp
        self.transport = transport
        self.root = Node("html")
        self.storage: Dict[str, str] = {}
        self.document_listeners: Dict[str, List[Any]] = {}
        self._install(hostname)

    # -- DOM plumbing ---------------------------------------------------------
    def wrap(self, node):
        if node is None:
            return None
        if isinstance(node, Element):
            return node
        return Element(node, self)

    def by_id(self, ident: str):
        for node in self.root.walk():
            if node.attrs.get("id") == ident:
                return self.wrap(node)
        return None

    def load_html(self, html: str):
        self.root.set_inner_html(html)

    # -- events ---------------------------------------------------------------
    def make_event(self, target: Element, kind: str, props=None):
        event = JSObject({
            "type": kind,
            "target": target,
            "clientY": 0.0,
            "clientX": 0.0,
            "button": 0.0,
            "key": "",
            "preventDefault": _as_native(lambda: UNDEFINED),
            "stopPropagation": _as_native(lambda: UNDEFINED),
        })
        for key, value in (props or {}).items():
            event.set(key, value)
        return event

    def dispatch(self, target: Element, event):
        kind = js_str(self.interp.get_property(event, "type"))
        node = target.node
        while node is not None:
            for listener in list(node.listeners.get(kind, [])):
                self.interp.call_any(listener, [event], this=self.wrap(node))
            handler_src = node.attrs.get("on" + kind)
            if handler_src:
                self.run_inline(handler_src, self.wrap(node), event)
            node = node.parent
        for listener in list(self.document_listeners.get(kind, [])):
            self.interp.call_any(listener, [event])
        return True

    def fire(self, target: Element, kind: str, **props):
        converted = {k: (float(v) if isinstance(v, (int, float)) and
                         not isinstance(v, bool) else v)
                     for k, v in props.items()}
        event = self.make_event(target, kind, converted)
        return self.dispatch(target, event)

    def run_inline(self, source: str, this_el, event):
        self.interp.eval_expr(source, {"this": this_el, "event": event})

    # -- globals --------------------------------------------------------------
    def _install(self, hostname: str):
        interp = self.interp
        page = self

        class DocumentHost:
            def js_get(self, prop):
                methods = {
                    "getElementById": lambda ident="": page.by_id(js_str(ident)),
                    "querySelector": lambda sel="": (
                        [page.wrap(n) for n in
                         query_all(page.root, js_str(sel))[:1]] or [None])[0],
                    "querySelectorAll": lambda sel="": JSArray(
                        [page.wrap(n) for n in query_all(page.root, js_str(sel))]),
                    "createElement": lambda tag="div": page.wrap(
                        Node(js_str(tag))),
                    "addEventListener": lambda kind="", fn=None, *_:
                        page.document_listeners.setdefault(
                            js_str(kind), []).append(fn),
                    "removeEventListener": lambda kind="", fn=None, *_: UNDEFINED,
                }
                if prop in methods:
                    return _as_native(methods[prop])
                if prop == "body":
                    return page.wrap(page.root)
                return UNDEFINED

            def js_set(self, prop, value):
                raise JSError(f"document.{prop} assignment unsupported")

        storage = self.storage

        class StorageHost:
            def js_get(self, prop):
                methods = {
                    "getItem": lambda key="": storage.get(js_str(key), None),
                    "setItem": lambda key="", value="":
                        storage.__setitem__(js_str(key), js_str(value)),
                    "removeItem": lambda key="": storage.pop(js_str(key), None)
                        and UNDEFINED,
                    "clear": lambda: storage.clear(),
                }
                if prop in methods:
                    return _as_native(methods[prop])
                return UNDEFINED

            def js_set(self, prop, value):
                storage[prop] = js_str(value)

        def fetch(url="", options=UNDEFINED):
            from tools.minijs import JSPromise, _make_error

            method = "GET"
            headers: Dict[str, str] = {}
            body = None
            if isinstance(options, JSObject):
                if options.get("method") is not UNDEFINED:
                    method = js_str(options.get("method"))
                header_obj = options.get("headers")
                if isinstance(header_obj, JSObject):
                    headers = {k: js_str(v) for k, v in header_obj.props.items()}
                if options.get("body") is not UNDEFINED:
                    body = js_str(options.get("body"))
            try:
                status, payload = self.transport(method, js_str(url), headers,
                                                 body)
            except Exception as exc:   # network-level failure → rejected fetch
                return JSPromise.reject(JSException(_make_error(str(exc))))
            from tools.minijs import _json_parse

            response = JSObject({
                "status": float(status),
                "ok": 200 <= status < 300,
                "statusText": _STATUS_TEXT.get(status, str(status)),
                "json": _as_native(lambda: JSPromise.resolve(
                    _json_parse(payload if payload else "null"))),
                "text": _as_native(lambda: JSPromise.resolve(payload or "")),
            })
            return JSPromise.resolve(response)
        fetch._js_native = True

        interp.define("document", DocumentHost())
        interp.define("localStorage", StorageHost())
        interp.define("location", JSObject({
            "protocol": "http:", "hostname": hostname, "href": f"http://{hostname}/",
        }))
        interp.define("window", interp.global_env.vars.setdefault(
            "window", JSObject()))
        interp.define("fetch", fetch)
        interp.define("navigator", JSObject({"clipboard": JSObject()}))


_STATUS_TEXT = {200: "OK", 201: "Created", 204: "No Content",
                400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
                404: "Not Found", 409: "Conflict", 422: "Unprocessable Entity",
                500: "Internal Server Error"}
