"""Chaos-smoke the control-plane resilience loop end to end (``make chaos-smoke``).

Deterministic by construction: the fake cluster's FaultPlan is seeded, the
circuit breakers run on an injected fake clock (sleep advances it — zero
real waiting), and the retry/backoff rng is pinned. The walk
(docs/ROBUSTNESS.md):

1. healthy fleet → probe round populates infra, ``/api/readyz`` is 200;
2. kill a host → injected failures grow the streak, the breaker opens after
   exactly ``breaker_failure_threshold`` failures, the next fan-out skips
   the host outright (zero round-trips, ``circuit_open`` outcome), queue
   scheduling refuses to spawn onto it, readiness flips to 503 naming the
   host, and the ``transport_breaker_open`` rule fires exactly once;
3. revive the host + elapse the cool-down → the half-open probe closes the
   breaker, the queued job finally spawns, readiness recovers, the alert
   resolves exactly once, and every breaker transition was counted exactly
   once.

Exit 0 = healthy.
"""
from __future__ import annotations

import json
import os
import random
import sys
import tempfile
import urllib.error
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"chaos-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def fetch(url: str):
    """(status, body) — urllib raises on >=400, readiness 503 is a result."""
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode()


class FakeClock:
    def __init__(self, start: float = 1000.0) -> None:
        self.now = start

    def __call__(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        self.now += seconds

    def advance(self, seconds: float) -> None:
        self.now += seconds


def main() -> int:
    from tensorhive_tpu.config import Config, HostConfig, set_config

    config = Config(config_dir=Path(tempfile.mkdtemp(prefix="tpuhive-chaos-")))
    config.ssh.num_retries = 1
    config.ssh.breaker_failure_threshold = 3
    config.ssh.breaker_cooldown_s = 30.0
    config.ssh.breaker_cooldown_jitter = 0.1
    config.ssh.breaker_half_open_probes = 1
    for name in ("vm-0", "vm-1"):
        config.hosts[name] = HostConfig(name=name, user="hive", backend="fake",
                                        accelerator_type="v5litepod-8", chips=4)
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine
    from tensorhive_tpu.db.migrations import ensure_schema

    engine = Engine(":memory:")
    ensure_schema(engine)
    set_engine(engine)

    from tensorhive_tpu.core.managers.manager import TpuHiveManager, set_manager
    from tensorhive_tpu.core.monitors.tpu import TpuMonitor
    from tensorhive_tpu.core.nursery import set_ops_factory
    from tensorhive_tpu.core.services.job_scheduling import JobSchedulingService
    from tensorhive_tpu.core.transport.base import (
        TransportManager,
        register_backend,
        set_transport_manager,
    )
    from tensorhive_tpu.core.transport.fake import (
        FakeCluster,
        FakeOpsFactory,
        FakeTransport,
        FaultPlan,
    )
    from tensorhive_tpu.core.transport.resilience import TransportResilience
    from tensorhive_tpu.db.models.job import Job, JobStatus
    from tensorhive_tpu.db.models.restriction import Restriction
    from tensorhive_tpu.db.models.task import Task
    from tensorhive_tpu.db.models.user import User
    from tensorhive_tpu.observability import get_registry
    from tensorhive_tpu.observability.alerts import AlertEngine, default_rule_pack
    from tensorhive_tpu.utils.timeutils import utcnow

    cluster = FakeCluster()
    register_backend("fake", lambda host, user=None, config=None: FakeTransport(
        host, cluster, user))
    for name in config.hosts:
        cluster.add_host(name, chips=4)
    set_ops_factory(FakeOpsFactory(cluster))

    clock = FakeClock()
    resilience = TransportResilience(config, clock=clock, sleep=clock.sleep,
                                     rng=random.Random(42))
    transports = TransportManager(config, resilience=resilience)
    set_transport_manager(transports)

    manager = TpuHiveManager(config=config, transport_manager=transports,
                             services=[])
    manager.configure_services_from_config()
    set_manager(manager)
    infra = manager.infrastructure_manager
    monitor = TpuMonitor(config)

    engine_rules = AlertEngine(default_rule_pack(monitoring_interval_s=2.0))
    notifications = []

    def evaluate(now):
        events = engine_rules.evaluate(now=now)
        notifications.extend(events)
        return events

    def breaker_events(rule, to):
        return [e for e in notifications if e["rule"] == rule and e["to"] == to]

    def transitions(host, to):
        family = get_registry().get("tpuhive_transport_breaker_transitions_total")
        return family.labels(host=host, to=to).value

    # a queued CPU-only job pinned to vm-0: the scheduling gate under test
    from datetime import timedelta

    Restriction(name="permissive", starts_at=utcnow() - timedelta(days=1),
                is_global=True).save()
    owner = User(username="alice", email="alice@example.com",
                 password="SuperSecret42").save()
    owner.add_role("user")
    job = Job(name="chaos-job", user_id=owner.id).save()
    Task(job_id=job.id, hostname="vm-0", command="python train.py").save()
    job.enqueue()
    scheduler = JobSchedulingService(config=config)
    scheduler.inject(infra, transports)

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    alert_now = 10_000.0
    try:
        # -- phase 1: healthy fleet ----------------------------------------
        monitor.update(transports, infra)
        check(infra.host_state("vm-0") == "ok", "vm-0 healthy after round 1")
        status, _ = fetch(f"{base}/readyz")
        check(status == 200, f"readyz is 200 on a healthy fleet (got {status})")
        evaluate(alert_now)
        check(not breaker_events("transport_breaker_open", "firing"),
              "no breaker alert while healthy")

        # -- phase 2: vm-0 dies --------------------------------------------
        cluster.host("vm-0").reachable = False
        monitor.update(transports, infra)              # 2 failures (attempt+retry)
        check(resilience.breaker("vm-0").consecutive_failures == 2,
              "round 1 against the dead host = attempt + one retry")
        monitor.update(transports, infra)              # 3rd failure trips it
        check(resilience.breaker("vm-0").state == "open",
              "breaker opened after exactly 3 injected failures")
        check(transports.open_circuit_hosts() == ["vm-0"],
              "manager reports vm-0 open-circuit")

        plan = cluster.set_fault_plan("vm-0", FaultPlan(seed=7))
        results = transports.run_on_all("uname", timeout=5.0)
        check("circuit open" in results["vm-0"].stderr
              and not results["vm-0"].ok,
              "run_on_all returns a synthetic circuit_open result")
        check(plan.calls == 0, "open circuit: zero round-trips reached vm-0")
        check(results["vm-1"].ok, "vm-1 unaffected by vm-0's breaker")

        health = infra.host_health()["vm-0"]
        check(health["state"] in ("degraded", "unreachable")
              and health["staleness_s"] is not None,
              f"infra retains last-known-good with staleness ({health['state']})")
        check("TPU" in infra.infrastructure["vm-0"],
              "last-known-good TPU subtree retained, not dropped")

        scheduler.do_run()
        check(Job.get(job.id).status is JobStatus.pending,
              "queued job NOT spawned onto the open-circuit host")

        status, body = fetch(f"{base}/readyz")
        doc = json.loads(body)
        check(status == 503, f"readyz is 503 while a breaker is open (got {status})")
        check(any(c["component"] == "transport" and not c["ok"]
                  and "vm-0" in c.get("reason", "")
                  for c in doc.get("components", [])),
              "readyz names vm-0 in the transport component")

        _, scrape = fetch(f"{base}/metrics")
        check('tpuhive_transport_breaker_state{host="vm-0"} 2' in scrape,
              "breaker gauge exports open (2) for vm-0")

        evaluate(alert_now + 5)
        evaluate(alert_now + 10)                       # re-evaluate: no dupes
        fired = breaker_events("transport_breaker_open", "firing")
        check(len(fired) == 1,
              f"transport_breaker_open fired exactly once (got {len(fired)})")

        # -- phase 3: vm-0 revives ------------------------------------------
        cluster.host("vm-0").reachable = True
        cluster.set_fault_plan("vm-0", None)
        clock.advance(34.0)                            # past cooldown + jitter
        monitor.update(transports, infra)              # half-open probe closes it
        check(resilience.breaker("vm-0").state == "closed",
              "half-open probe restored the breaker to closed")
        check(infra.host_state("vm-0") == "ok", "vm-0 healthy again in infra")

        scheduler.do_run()
        check(Job.get(job.id).status is JobStatus.running,
              "queued job spawns once the host is back")

        status, _ = fetch(f"{base}/readyz")
        check(status == 200, f"readyz back to 200 after recovery (got {status})")

        _, scrape = fetch(f"{base}/metrics")
        check('tpuhive_transport_breaker_state{host="vm-0"} 0' in scrape,
              "breaker gauge exports closed (0) after recovery")

        evaluate(alert_now + 15)
        evaluate(alert_now + 20)
        resolved = breaker_events("transport_breaker_open", "resolved")
        check(len(resolved) == 1,
              f"transport_breaker_open resolved exactly once (got {len(resolved)})")

        for to in ("open", "half_open", "closed"):
            check(transitions("vm-0", to) == 1,
                  f"breaker transition to={to} counted exactly once")
    finally:
        server.stop()
        transports.close()
        set_transport_manager(None)
        set_manager(None)
        set_ops_factory(None)

    if PROBLEMS:
        print(f"chaos-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("chaos-smoke: OK — breaker opened after N injected failures, "
          "fan-out + scheduler skipped the host, readiness degraded and "
          "recovered, alert fired/resolved exactly once")
    return 0


if __name__ == "__main__":
    sys.exit(main())
