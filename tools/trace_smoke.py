"""Smoke-test request tracing + on-demand profiling end to end
(``make trace-smoke``; docs/OBSERVABILITY.md "Request tracing & profiling").

Boots the real daemon surface in-process — WSGI app over a real socket, a
live GenerationService pump, in-memory DB, profiling enabled into a temp
artifact dir — then walks the whole diagnosable-serving story over HTTP:

1. stream one authenticated ``POST /api/generate`` request and read its
   ``X-Request-Id`` from the response header + ``done`` chunk;
2. ``GET /api/admin/requests`` must show that request with every phase
   timed and sanely ordered (queue <= ttft <= total, prefill > 0, tokens
   exact) — and zero new post-warmup recompiles while it ran;
3. ``GET /api/admin/traces`` must carry the queue/prefill/decode/stream
   spans labelled with the same request_id;
4. ``POST /api/admin/profile`` on this CPU backend must produce a
   non-empty trace artifact on disk (and answer 409 to a concurrent
   capture);
5. the ``/api/metrics`` scrape must export the new
   ``tpuhive_generate_queue_wait_seconds`` histogram and the
   ``tpuhive_device_hbm_live_bytes`` gauge.

Exit 0 = healthy.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.error
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"trace-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def request(url: str, body=None, headers=None, method=None):
    """(status, text) over real HTTP; >=400 is a result, not an exception."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tensorhive_tpu.config import Config, set_config

    workdir = tempfile.mkdtemp(prefix="tpuhive-trace-smoke-")
    config = Config(config_dir=Path(workdir))
    config.api.secret_key = "trace-smoke-secret"
    config.generation.enabled = True
    config.generation.slots = 2
    config.generation.queue_depth = 4
    config.generation.max_len = 96
    config.generation.interval_s = 0.01
    config.generation.kv_quant = "off"
    config.profiling.enabled = True
    config.profiling.artifact_dir = str(Path(workdir) / "profiles")
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine
    from tensorhive_tpu.db.migrations import ensure_schema

    engine_db = Engine(":memory:")
    ensure_schema(engine_db)
    set_engine(engine_db)

    from tensorhive_tpu.db.models import User

    admin = User(username="smoke-admin", email="smoke@example.com",
                 password="SuperSecret42").save()
    admin.add_role("user")
    admin.add_role("admin")

    from tensorhive_tpu import serving
    from tensorhive_tpu.core.services.generation import GenerationService

    generation = GenerationService(config=config)     # builds + warms engine
    slot_engine = serving.get_engine()
    assert slot_engine is not None, "engine did not publish"
    step_execs = slot_engine.step_executable._cache_size()
    prefill_execs = slot_engine.prefill_executable._cache_size()
    generation.start()

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    try:
        status, body, _ = request(f"{base}/user/login", body={
            "username": "smoke-admin", "password": "SuperSecret42"})
        check(status == 200, f"admin login over HTTP (got {status})")
        auth = {"Authorization": "Bearer " + json.loads(body)["accessToken"]}

        # -- 1: one streamed generation, id on header + done chunk ---------
        new_tokens = 6
        status, body, headers = request(f"{base}/generate", body={
            "promptTokens": [3, 4, 5, 6, 7, 8, 9, 10],
            "maxNewTokens": new_tokens, "temperature": 0}, headers=auth)
        check(status == 200, f"POST /generate streamed (got {status})")
        request_id = headers.get("X-Request-Id")
        check(bool(request_id), "X-Request-Id response header present")
        lines = [json.loads(line) for line in body.strip().splitlines()]
        done = lines[-1]
        check(done.get("outcome") == "completed",
              f"stream completed (got {done})")
        check(done.get("requestId") == request_id,
              "done chunk requestId matches the response header")

        # -- 2: the ledger has the request, phases sanely ordered ----------
        status, body, _ = request(f"{base}/admin/requests", headers=auth)
        check(status == 200, f"GET /admin/requests (got {status})")
        rows = [row for row in json.loads(body)["requests"]
                if row["requestId"] == request_id]
        check(len(rows) == 1, "exactly one ledger row for the request")
        if rows:
            row = rows[0]
            check(row["outcome"] == "completed", "ledger outcome completed")
            check(row["tokens"] == new_tokens,
                  f"ledger token count {row['tokens']} == {new_tokens}")
            phases_present = all(
                row[key] is not None for key in
                ("queueMs", "prefillMs", "ttftMs", "decodeMs", "totalMs"))
            check(phases_present, f"every phase timed: {row}")
            if phases_present:
                check(row["queueMs"] <= row["ttftMs"] <= row["totalMs"],
                      f"queue {row['queueMs']} <= ttft {row['ttftMs']} <= "
                      f"total {row['totalMs']}")
                check(row["prefillMs"] > 0,
                      f"prefill > 0 (got {row['prefillMs']})")
            check(row["prefillBucket"] == 16 and
                  row["prefillCompile"] == "hit",
                  f"prefill bucket 16 reused a warmed executable: {row}")

        check(slot_engine.step_executable._cache_size() == step_execs
              and slot_engine.prefill_executable._cache_size()
              == prefill_execs,
              "zero new post-warmup recompiles while the request ran")

        # -- 3: spans share the request_id ---------------------------------
        status, body, _ = request(f"{base}/admin/traces?kind=generate",
                                  headers=auth)
        check(status == 200, f"GET /admin/traces (got {status})")
        names = {span["name"] for span in json.loads(body)["spans"]
                 if span["attrs"].get("request_id") == request_id}
        check({"generate.queue", "generate.prefill", "generate.decode",
               "generate.stream"} <= names,
              f"queue/prefill/decode/stream spans share the id (got "
              f"{sorted(names)})")

        # -- 4: a profile capture writes a real artifact -------------------
        status, body, _ = request(f"{base}/admin/profile",
                                  body={"durationS": 0.2}, headers=auth)
        check(status == 200, f"POST /admin/profile (got {status}: {body})")
        if status == 200:
            doc = json.loads(body)
            check(doc["files"] and doc["bytes"] > 0,
                  f"non-empty trace artifact ({doc['files']}, "
                  f"{doc['bytes']} bytes)")
            on_disk = [Path(doc["artifactDir"]) / name
                       for name in doc["files"]]
            check(all(path.is_file() and path.stat().st_size >= 0
                      for path in on_disk)
                  and any(path.stat().st_size > 0 for path in on_disk),
                  "artifact files exist on disk with real bytes")

        status, body, _ = request(f"{base}/admin/profile/memory",
                                  headers=auth)
        check(status == 200, f"GET /admin/profile/memory (got {status})")
        if status == 200:
            doc = json.loads(body)
            check(doc["totalLiveBytes"] > 0,
                  f"live device bytes visible ({doc['totalLiveBytes']})")

        # -- 5: new histogram + HBM gauge in the scrape --------------------
        status, scrape, _ = request(f"{base}/metrics")
        check(status == 200, f"GET /metrics (got {status})")
        check("tpuhive_generate_queue_wait_seconds_bucket" in scrape,
              "queue-wait histogram in the exposition")
        check("tpuhive_device_hbm_live_bytes{" in scrape,
              "per-device HBM gauge in the exposition")
    finally:
        server.stop()
        generation.shutdown()
        generation.join(timeout=5)

    # -- 6 (TPUHIVE_LOCK_WITNESS=1 only): the traced run doubled as a lock
    # witness — no ABBA inversions, observed order ⊆ static TH-LOCK graph
    from tensorhive_tpu.utils import lockwitness

    if lockwitness.witness_enabled():
        dump_path = Path(workdir) / "lock-witness.json"
        snap = lockwitness.dump(str(dump_path))
        check(snap["locks"], "witness observed named locks "
              f"({len(snap['locks'])} names, {len(snap['edges'])} edges)")
        check(not snap["inversions"],
              f"zero runtime lock inversions ({snap['inversions']})")
        from tools.analysis.rules.locks import compare_witness

        ok, lines = compare_witness(
            dump_path, Path(__file__).resolve().parent.parent)
        for line in lines:
            print(f"trace-smoke: {line}")
        check(ok, "observed lock-order edges ⊆ static TH-LOCK graph")

    if PROBLEMS:
        print(f"trace-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("trace-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
