"""Smoke the host membership plane end to end (``make agent-smoke``).

A real :class:`HostAgent` posts over a real socket to the real WSGI app;
everything time-dependent runs on explicit timestamps (lease sweeps and
alert evaluation take ``now=``), so the walk is deterministic and takes
milliseconds. The walk (docs/ROBUSTNESS.md "Host membership & leases"):

1. an agent on an UNCONFIGURED host reports in → dynamic join, lease live,
   pushed telemetry visible, and the SSH fan-out issues zero round-trips to
   it (the legacy host keeps being pulled);
2. a queued job spawns onto the agent host while it is live;
3. the host is preempted mid-job and the agent falls silent → the lease
   walks suspect → unreachable within 3x the heartbeat interval,
   ``host_lease_expired`` fires exactly once, readiness 503s naming the
   host, new work refuses to land there, and the running job is reaped
   without crashing the scheduling tick;
4. the agent restarts (new incarnation) and re-joins → live again, the
   alert resolves exactly once, queued work flows, and zero stale-sequence
   reports were ever counted.

Exit 0 = healthy.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.error
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

TOKEN = "smoke-agent-token"
PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"agent-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def fetch(url: str):
    """(status, body) — urllib raises on >=400, readiness 503 is a result."""
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode()


def main() -> int:
    from tensorhive_tpu.config import Config, HostConfig, set_config

    config = Config(config_dir=Path(tempfile.mkdtemp(prefix="tpuhive-agent-")))
    config.agent.token = TOKEN                         # heartbeat 2s → suspect 4s, ttl 6s
    config.hosts["legacy-0"] = HostConfig(
        name="legacy-0", user="hive", backend="fake",
        accelerator_type="v5litepod-8", chips=4)
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine
    from tensorhive_tpu.db.migrations import ensure_schema

    engine = Engine(":memory:")
    ensure_schema(engine)
    set_engine(engine)

    from tensorhive_tpu.core.agent import HostAgent
    from tensorhive_tpu.core.managers.manager import TpuHiveManager, set_manager
    from tensorhive_tpu.core.monitors.tpu import TpuMonitor
    from tensorhive_tpu.core.nursery import set_ops_factory
    from tensorhive_tpu.core.services.job_scheduling import JobSchedulingService
    from tensorhive_tpu.core.services.monitoring import MonitoringService
    from tensorhive_tpu.core.transport.base import (
        TransportManager,
        register_backend,
        set_transport_manager,
    )
    from tensorhive_tpu.core.transport.fake import (
        FakeCluster,
        FakeOpsFactory,
        FakeTransport,
        FaultPlan,
    )
    from tensorhive_tpu.db.models.job import Job, JobStatus
    from tensorhive_tpu.db.models.restriction import Restriction
    from tensorhive_tpu.db.models.task import Task
    from tensorhive_tpu.db.models.user import User
    from tensorhive_tpu.observability import get_registry
    from tensorhive_tpu.observability.alerts import AlertEngine, default_rule_pack
    from tensorhive_tpu.utils.timeutils import utcnow

    cluster = FakeCluster()
    register_backend("fake", lambda host, user=None, config=None: FakeTransport(
        host, cluster, user))
    cluster.add_host("legacy-0", chips=4)
    cluster.add_host("agent-0", chips=4)               # real machine, NOT in config
    set_ops_factory(FakeOpsFactory(cluster))

    transports = TransportManager(config)
    set_transport_manager(transports)
    manager = TpuHiveManager(config=config, transport_manager=transports,
                             services=[])
    set_manager(manager)
    infra = manager.infrastructure_manager
    monitor = TpuMonitor(config)
    monitoring = MonitoringService(config=config)
    monitoring.inject(infra, transports)
    scheduler = JobSchedulingService(config=config)
    scheduler.inject(infra, transports)

    engine_rules = AlertEngine(default_rule_pack(monitoring_interval_s=2.0))
    notifications = []

    def evaluate(now):
        notifications.extend(engine_rules.evaluate(now=now))

    def lease_events(rule, to):
        return [e for e in notifications if e["rule"] == rule and e["to"] == to]

    def report_count(outcome):
        family = get_registry().get("tpuhive_agent_reports_total")
        return family.labels(host="agent-0", outcome=outcome).value

    from datetime import timedelta

    Restriction(name="permissive", starts_at=utcnow() - timedelta(days=1),
                is_global=True).save()
    owner = User(username="alice", email="alice@example.com",
                 password="SuperSecret42").save()
    owner.add_role("user")

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    alert_now = 10_000.0
    try:
        # -- phase 1: dynamic join over the real socket ---------------------
        agent = HostAgent(
            "agent-0", base, TOKEN, incarnation="inc-1",
            host_info={"accelerator_type": "v5litepod-8", "chips": 4},
            collect=lambda: json.loads(cluster.probe_json("agent-0")))
        status, doc = agent.report_once()
        check(status == 200 and doc["outcome"] == "accepted",
              f"first report accepted over the socket (got {status} {doc})")
        lease = infra.host_lease("agent-0")
        check(lease["state"] == "live" and lease["source"] == "agent",
              "agent-0 holds a live agent lease")
        check("agent-0" in manager.config.hosts,
              "unconfigured host joined dynamically")
        check(len(infra.infrastructure["agent-0"].get("TPU", {})) == 4,
              "pushed telemetry landed (4 chips)")

        # a duplicated heartbeat (at-least-once delivery) is absorbed
        dup_agent = HostAgent(
            "agent-0", base, TOKEN, incarnation="inc-1",
            fault_plan=FaultPlan(duplicate_reports=1),
            collect=lambda: json.loads(cluster.probe_json("agent-0")))
        dup_agent.seq = agent.seq
        dup_agent.report_once()
        check(report_count("duplicate") == 1.0,
              "duplicated report counted once as duplicate, lease unharmed")

        # hybrid fan-out: the legacy host is pulled, the agent host is not
        legacy_plan = cluster.set_fault_plan("legacy-0", FaultPlan())
        monitor.update(transports, infra)
        check(legacy_plan.calls > 0 and "TPU" in infra.infrastructure["legacy-0"],
              "legacy host still pulled via the transport fan-out")
        commands = get_registry().get("tpuhive_transport_commands_total")
        agent_cmds = sum(child.value for labels, child in commands.children()
                         if labels[0] == "agent-0")
        check(agent_cmds == 0, "ZERO transport round-trips to the agent host")

        _, scrape = fetch(f"{base}/metrics")
        check('tpuhive_host_lease_state{host="agent-0"} 0' in scrape,
              "lease gauge exports live (0)")

        evaluate(alert_now)
        check(not lease_events("host_lease_expired", "firing"),
              "no lease alert while live")

        # -- phase 2: a queued job lands on the live agent host -------------
        job = Job(name="agent-job", user_id=owner.id).save()
        Task(job_id=job.id, hostname="agent-0", command="python train.py").save()
        job.enqueue()
        scheduler.do_run()
        check(Job.get(job.id).status is JobStatus.running,
              "queued job spawned onto the live agent host")

        # -- phase 3: preemption mid-job + silence --------------------------
        cluster.preempt_host("agent-0")                # processes killed
        t0 = infra.host_lease("agent-0")["last_report_ts"]
        monitoring.sweep_leases(now=t0 + 4.5)          # past 2x heartbeat
        check(infra.host_lease("agent-0")["state"] == "suspect",
              "silent host suspect within 2x heartbeat")
        evaluate(alert_now + 5)
        monitoring.sweep_leases(now=t0 + 6.5)          # past 3x heartbeat
        check(infra.host_lease("agent-0")["state"] == "unreachable",
              "lease expired within 3x heartbeat")
        evaluate(alert_now + 10)
        evaluate(alert_now + 15)                       # re-evaluate: no dupes
        fired = lease_events("host_lease_expired", "firing")
        check(len(fired) == 1,
              f"host_lease_expired fired exactly once (got {len(fired)})")

        status, body = fetch(f"{base}/readyz")
        doc = json.loads(body)
        check(status == 503, f"readyz 503 while a lease is expired (got {status})")
        check(any(c["component"] == "membership" and not c["ok"]
                  and "agent-0" in c.get("reason", "")
                  for c in doc.get("components", [])),
              "readyz names agent-0 in the membership component")

        scheduler.do_run()                             # must not raise
        check(Job.get(job.id).status is not JobStatus.running,
              "preempted host's job reaped without a hung tick")

        job2 = Job(name="post-expiry-job", user_id=owner.id).save()
        Task(job_id=job2.id, hostname="agent-0", command="python eval.py").save()
        job2.enqueue()
        scheduler.do_run()
        check(Job.get(job2.id).status is JobStatus.pending,
              "no new work lands on the expired host")

        _, scrape = fetch(f"{base}/metrics")
        check('tpuhive_host_lease_state{host="agent-0"} 2' in scrape,
              "lease gauge exports unreachable (2)")

        # -- phase 4: the agent restarts and re-joins -----------------------
        cluster.restore_host("agent-0")
        rejoined = HostAgent(
            "agent-0", base, TOKEN, incarnation="inc-2",
            collect=lambda: json.loads(cluster.probe_json("agent-0")))
        status, doc = rejoined.report_once()
        check(status == 200 and doc["outcome"] == "accepted",
              "re-join report accepted under a fresh incarnation")
        check(infra.host_lease("agent-0")["state"] == "live",
              "lease live again after re-join")

        evaluate(alert_now + 20)
        evaluate(alert_now + 25)
        resolved = lease_events("host_lease_expired", "resolved")
        check(len(resolved) == 1,
              f"host_lease_expired resolved exactly once (got {len(resolved)})")

        status, _ = fetch(f"{base}/readyz")
        check(status == 200, f"readyz back to 200 after re-join (got {status})")

        scheduler.do_run()
        check(Job.get(job2.id).status is JobStatus.running,
              "queued job spawns once the host re-joined")
        check(report_count("out_of_order") == 0.0,
              "zero stale-sequence regressions across the whole churn")
    finally:
        server.stop()
        transports.close()
        set_transport_manager(None)
        set_manager(None)
        set_ops_factory(None)

    if PROBLEMS:
        print(f"agent-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("agent-smoke: OK — dynamic join went live with zero SSH round-trips, "
          "silence walked suspect→expired on schedule with exactly-once "
          "alerting, the preempted host's job was reaped without crashing the "
          "tick, and re-join restored service cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
