"""CPU-backend decode smoke: tiny-config generate round-trip.

Exercises the serving fast path end to end on the CPU backend — donated
in-place KV cache, bucketed prefill, greedy + top-k sampling — and prints
tokens/s plus the ``tpuhive_decode_compile_total`` counter state. Exits
nonzero if the round-trip breaks (prompt not preserved, wrong shape,
out-of-vocab tokens, or more compiled executables than prompt buckets).

Run via ``make decode-smoke``; CI runs it right after the static-analysis
gate so a decode-path regression fails before the full suite spins up.
"""
from __future__ import annotations

import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402

# the axon TPU plugin overrides the env var; pin through the config API
# (same discipline as tests/conftest.py and bench.probe_backend)
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from tensorhive_tpu.models import decode  # noqa: E402
from tensorhive_tpu.models.transformer import PRESETS, TransformerLM  # noqa: E402
from tensorhive_tpu.observability import get_registry  # noqa: E402


def main() -> int:
    config = PRESETS["tiny"]
    batch, new_tokens = 2, 8
    params = TransformerLM.init(jax.random.PRNGKey(0), config)

    # mixed lengths on purpose: 20/28 share bucket 32, 40/56 share 64 —
    # the compile counter must show one generate executable per bucket
    prompt_lens = (20, 28, 40, 56)
    counter = get_registry().counter(
        "tpuhive_decode_compile_total",
        "decode-path executables: miss = new shape compiled, "
        "hit = shape-cache reuse",
        labels=("fn", "event"))
    failures = []
    generated = 0
    buckets = set()
    started = time.perf_counter()
    for index, prompt_len in enumerate(prompt_lens):
        prompt = jax.random.randint(
            jax.random.PRNGKey(index), (batch, prompt_len), 0,
            config.vocab_size, dtype=jnp.int32)
        buckets.add(decode._prefill_bucket(
            prompt_len - 1, config.max_seq_len - new_tokens - 1))
        temperature, top_k = ((0.0, None) if index % 2 == 0 else (0.8, 10))
        out = decode.generate(params, config, prompt,
                              max_new_tokens=new_tokens,
                              temperature=temperature, top_k=top_k)
        out = jax.block_until_ready(out)
        generated += batch * new_tokens
        if out.shape != (batch, prompt_len + new_tokens):
            failures.append(f"P={prompt_len}: shape {out.shape}")
        if not bool((out[:, :prompt_len] == prompt).all()):
            failures.append(f"P={prompt_len}: prompt not preserved")
        if not 0 <= int(out.min()) <= int(out.max()) < config.vocab_size:
            failures.append(f"P={prompt_len}: out-of-vocab token")
    elapsed = time.perf_counter() - started

    misses = int(counter.labels(fn="generate", event="miss").value)
    hits = int(counter.labels(fn="generate", event="hit").value)
    # greedy and sampled steps are distinct executables by design (the
    # sampling MODE is static), so the budget is one per (bucket, mode)
    budget = len(buckets) * 2
    if misses > budget:
        failures.append(
            f"{misses} generate executables for {len(buckets)} buckets "
            f"x 2 sampling modes (budget {budget})")

    print(f"decode-smoke: {generated} tokens in {elapsed:.2f}s "
          f"({generated / elapsed:.1f} tok/s incl. compiles) | "
          f"buckets={sorted(buckets)} compile miss={misses} hit={hits}")
    for failure in failures:
        print(f"decode-smoke FAILURE: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
