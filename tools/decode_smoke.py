"""CPU-backend decode smoke: tiny-config generate round-trip.

Exercises the serving fast path end to end on the CPU backend — donated
in-place KV cache, bucketed prefill, greedy + top-k sampling — and prints
tokens/s plus the ``tpuhive_decode_compile_total`` counter state. Exits
nonzero if the round-trip breaks (prompt not preserved, wrong shape,
out-of-vocab tokens, or more compiled executables than prompt buckets).

Also exercises the ``paged_kernel`` dispatch knob (docs/SERVING.md): a
paged engine per knob value — ``on`` (fused pallas kernel, interpret mode
on CPU), ``off`` (XLA page gather) and ``auto`` (gather on this backend) —
must resolve to the documented dispatch and emit IDENTICAL greedy tokens,
so flipping the knob can never change what the model says.

Run via ``make decode-smoke``; CI runs it right after the static-analysis
gate so a decode-path regression fails before the full suite spins up.
"""
from __future__ import annotations

import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402

# the axon TPU plugin overrides the env var; pin through the config API
# (same discipline as tests/conftest.py and bench.probe_backend)
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from tensorhive_tpu.models import decode  # noqa: E402
from tensorhive_tpu.models.transformer import PRESETS, TransformerLM  # noqa: E402
from tensorhive_tpu.observability import get_registry  # noqa: E402


def main() -> int:
    config = PRESETS["tiny"]
    batch, new_tokens = 2, 8
    params = TransformerLM.init(jax.random.PRNGKey(0), config)

    # mixed lengths on purpose: 20/28 share bucket 32, 40/56 share 64 —
    # the compile counter must show one generate executable per bucket
    prompt_lens = (20, 28, 40, 56)
    counter = get_registry().counter(
        "tpuhive_decode_compile_total",
        "decode-path executables: miss = new shape compiled, "
        "hit = shape-cache reuse",
        labels=("fn", "event"))
    failures = []
    generated = 0
    buckets = set()
    started = time.perf_counter()
    for index, prompt_len in enumerate(prompt_lens):
        prompt = jax.random.randint(
            jax.random.PRNGKey(index), (batch, prompt_len), 0,
            config.vocab_size, dtype=jnp.int32)
        buckets.add(decode._prefill_bucket(
            prompt_len - 1, config.max_seq_len - new_tokens - 1))
        temperature, top_k = ((0.0, None) if index % 2 == 0 else (0.8, 10))
        out = decode.generate(params, config, prompt,
                              max_new_tokens=new_tokens,
                              temperature=temperature, top_k=top_k)
        out = jax.block_until_ready(out)
        generated += batch * new_tokens
        if out.shape != (batch, prompt_len + new_tokens):
            failures.append(f"P={prompt_len}: shape {out.shape}")
        if not bool((out[:, :prompt_len] == prompt).all()):
            failures.append(f"P={prompt_len}: prompt not preserved")
        if not 0 <= int(out.min()) <= int(out.max()) < config.vocab_size:
            failures.append(f"P={prompt_len}: out-of-vocab token")
    elapsed = time.perf_counter() - started

    # -- paged_kernel dispatch knob: on / off / auto must agree ------------
    import dataclasses

    from tensorhive_tpu.serving.engine import SlotEngine

    f32_config = dataclasses.replace(config, dtype=jnp.float32,
                                     use_flash=False, remat=False,
                                     max_seq_len=128)
    f32_params = TransformerLM.init(jax.random.PRNGKey(0), f32_config)
    knob_prompt = list(range(3, 21))
    expected_dispatch = {"on": "pallas", "off": "xla", "auto": "xla"}
    knob_tokens = {}
    for knob in ("on", "off", "auto"):
        engine = SlotEngine(f32_params, f32_config, slots=2, max_len=64,
                            queue_depth=4, page_size=16, paged_kernel=knob,
                            kv_quant="off")
        dispatch = engine.stats()["pagedKernel"]
        if dispatch != expected_dispatch[knob]:
            failures.append(
                f"paged_kernel={knob!r} resolved to {dispatch!r} on the "
                f"CPU backend, wanted {expected_dispatch[knob]!r}")
        handle = engine.submit(knob_prompt, max_new_tokens=new_tokens)
        while engine.has_work():
            engine.step()
        knob_tokens[knob] = handle.result(timeout_s=10)["tokens"]
    if not knob_tokens["on"] == knob_tokens["off"] == knob_tokens["auto"]:
        failures.append(
            f"paged_kernel dispatches disagree on greedy tokens: "
            f"{ {k: v[:4] for k, v in knob_tokens.items()} }...")

    misses = int(counter.labels(fn="generate", event="miss").value)
    hits = int(counter.labels(fn="generate", event="hit").value)
    # greedy and sampled steps are distinct executables by design (the
    # sampling MODE is static), so the budget is one per (bucket, mode)
    budget = len(buckets) * 2
    if misses > budget:
        failures.append(
            f"{misses} generate executables for {len(buckets)} buckets "
            f"x 2 sampling modes (budget {budget})")

    print(f"decode-smoke: {generated} tokens in {elapsed:.2f}s "
          f"({generated / elapsed:.1f} tok/s incl. compiles) | "
          f"buckets={sorted(buckets)} compile miss={misses} hit={hits} | "
          f"paged_kernel on/off/auto agree "
          f"({len(knob_tokens['on'])} greedy tokens)")
    for failure in failures:
        print(f"decode-smoke FAILURE: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
