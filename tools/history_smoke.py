"""Smoke-test the time-aware telemetry plane end to end
(``make history-smoke``; docs/OBSERVABILITY.md "History, SLOs & flight
recorder").

Boots the real daemon surface — WSGI app over a real socket, a live
GenerationService pump, a HistoryService sampling every 0.05 s, in-memory
DB — around a flight-recorder-equipped engine wired to a seeded
:class:`ServingFaultPlan`, then proves the observability contract over
HTTP:

1. a streamed ``POST /api/generate`` request completes and
   ``GET /api/admin/history`` answers with **>= 2 samples** of
   ``tpuhive_generate_queue_depth`` (the ring TSDB is live, windows carry
   min/mean/max/last/count);
2. the ``/api/metrics`` scrape carries a ``tpuhive_slo_burn_rate`` gauge —
   the SLO engine computed a burn over the sampled history (0.0 for
   healthy traffic, never absent once traffic flowed);
3. ``GET /api/admin/flightrec`` serves the live tick ring with the served
   request's work stamped into it;
4. an injected fatal (``fail_next("step")``) kills the stream terminally,
   the supervisor restarts the engine, and ``GET
   /api/admin/flightrec/dumps`` serves **exactly one** crash dump whose
   last tick shows the fault injection and whose in-flight rows include
   the doomed request.

Engines run the f32 tiny config (like the unit suite). Exit 0 = healthy.
"""
from __future__ import annotations

import dataclasses
import json
import os
import shutil
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

SEED = 42
PROMPT = [3, 4, 5, 6, 7, 8, 9, 10]
NEW_TOKENS = 8
SAMPLE_INTERVAL_S = 0.05

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"history-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def request(url: str, body=None, headers=None, method=None):
    """(status, text, headers) over real HTTP; >=400 is a result."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def stream_request(base: str, auth: dict, max_new: int):
    """Stream one generate request; returns the parsed NDJSON lines."""
    req = urllib.request.Request(
        f"{base}/generate",
        data=json.dumps({"promptTokens": PROMPT, "maxNewTokens": max_new,
                         "temperature": 0}).encode(),
        headers={"Content-Type": "application/json", **auth})
    lines = []
    with urllib.request.urlopen(req, timeout=60) as resp:
        while True:
            raw = resp.readline()
            if not raw:
                break
            lines.append(json.loads(raw))
    return lines


def wait_for(predicate, timeout_s: float = 10.0, interval_s: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tensorhive_tpu.config import Config, set_config

    config_dir = Path("/tmp/tpuhive-history-smoke")
    shutil.rmtree(config_dir, ignore_errors=True)     # stale dumps poison
    config = Config(config_dir=config_dir)            # the exactly-one gate
    config.api.secret_key = "history-smoke-secret"
    config.generation.enabled = True
    config.generation.interval_s = 0.01
    config.generation.transient_backoff_s = 0.0
    config.history.sample_interval_s = SAMPLE_INTERVAL_S
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine as set_db
    from tensorhive_tpu.db.migrations import ensure_schema

    engine_db = Engine(":memory:")
    ensure_schema(engine_db)
    set_db(engine_db)

    from tensorhive_tpu.db.models import User

    admin = User(username="smoke-admin", email="smoke@example.com",
                 password="SuperSecret42").save()
    admin.add_role("user")
    admin.add_role("admin")

    from tensorhive_tpu import serving
    from tensorhive_tpu.core.services.generation import (
        GenerationService,
        build_flight_recorder,
    )
    from tensorhive_tpu.core.services.history import HistoryService
    from tensorhive_tpu.models.transformer import PRESETS, TransformerLM
    from tensorhive_tpu.serving.engine import SlotEngine
    from tensorhive_tpu.serving.faults import ServingFaultPlan

    f32_tiny = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32,
                                   use_flash=False, remat=False,
                                   max_seq_len=128)
    params = TransformerLM.init(jax.random.PRNGKey(0), f32_tiny)

    plan = ServingFaultPlan(seed=SEED)
    print(f"history-smoke: seed={SEED} "
          f"sample_interval_s={SAMPLE_INTERVAL_S}")

    def factory():
        engine = SlotEngine(params, f32_tiny, slots=2, max_len=96,
                            queue_depth=4, kv_quant="off", fault_plan=plan,
                            flight_recorder=build_flight_recorder(
                                config.generation))
        engine.warmup(prompt_lens=(len(PROMPT),))
        return engine

    generation = GenerationService(config=config, engine=factory(),
                                   engine_factory=factory)
    generation.start()
    history_service = HistoryService(config=config)
    history_service.start()

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    try:
        status, body, _ = request(f"{base}/user/login", body={
            "username": "smoke-admin", "password": "SuperSecret42"})
        check(status == 200, f"admin login over HTTP (got {status})")
        auth = {"Authorization": "Bearer " + json.loads(body)["accessToken"]}

        # -- 1: serve a request, then read its trace out of the TSDB ------
        lines = stream_request(base, auth, NEW_TOKENS)
        check(lines[-1].get("outcome") == "completed",
              f"baseline stream completed ({lines[-1]})")
        # let the 0.05s sampler land a few post-request passes
        time.sleep(SAMPLE_INTERVAL_S * 6)

        def depth_samples():
            status, body, _ = request(
                f"{base}/admin/history?series=tpuhive_generate_queue_depth",
                headers=auth)
            if status != 200:
                return -1
            points = json.loads(body)["series"].get(
                "tpuhive_generate_queue_depth", [])
            return sum(point["count"] for point in points)

        check(wait_for(lambda: depth_samples() >= 2, timeout_s=5.0),
              f"history holds >= 2 queue-depth samples "
              f"(got {depth_samples()})")

        status, body, _ = request(f"{base}/admin/history", headers=auth)
        payload = json.loads(body)
        check(status == 200 and payload["sampleIntervalS"] ==
              SAMPLE_INTERVAL_S,
              "history endpoint reports the configured sampling cadence")
        depth_points = payload["series"].get(
            "tpuhive_generate_queue_depth", [])
        check(all(set(point) == {"ts", "min", "mean", "max", "last",
                                 "count"} for point in depth_points),
              "windows carry min/mean/max/last/count aggregates")

        # -- 2: the SLO engine exported a burn gauge over that history -----
        # a second request makes the outcome counters GROW between samples
        # (a counter born mid-run at its final value has no in-window
        # increase, so the burn stays None until traffic actually flows)
        lines = stream_request(base, auth, NEW_TOKENS)
        check(lines[-1].get("outcome") == "completed",
              "second request completed (burn-rate traffic)")

        def burn_gauge_lines():
            status, scrape, _ = request(f"{base}/metrics")
            if status != 200:
                return []
            return [line for line in scrape.splitlines()
                    if line.startswith("tpuhive_slo_burn_rate{")]

        check(wait_for(lambda: len(burn_gauge_lines()) >= 1, timeout_s=5.0),
              f"tpuhive_slo_burn_rate gauge in the scrape "
              f"({burn_gauge_lines()[:2]})")

        # -- 3: the live flight-recorder ring shows the served work --------
        status, body, _ = request(f"{base}/admin/flightrec", headers=auth)
        ring = json.loads(body)
        check(status == 200 and ring["engineUp"] and ring["recorded"] >= 1,
              f"live flightrec ring is up with recorded ticks "
              f"(got {status}, recorded={ring.get('recorded')})")
        check(sum(t["admitted"] for t in ring["ticks"]) >= 1,
              "ring ticks stamp the served request's admission")

        # -- 4: injected fatal -> exactly one crash dump -------------------
        plan.fail_next("step", 1)
        lines = stream_request(base, auth, max_new=24)
        check("error" in lines[-1],
              f"injected fatal ended the stream terminally ({lines[-1]})")
        check(wait_for(lambda: serving.get_engine() is not None,
                       timeout_s=10.0),
              "engine restarted after the fatal")

        status, body, _ = request(f"{base}/admin/flightrec/dumps",
                                  headers=auth)
        dumps = json.loads(body)["dumps"]
        check(status == 200 and len(dumps) == 1,
              f"exactly one crash dump after one fatal (got {len(dumps)})")
        status, body, _ = request(
            f"{base}/admin/flightrec/dumps?file={dumps[0]['file']}",
            headers=auth)
        dump = json.loads(body)
        check(status == 200 and "DeviceLostError" in dump.get("reason", ""),
              f"dump names the fatal ({dump.get('reason')})")
        check(dump["ticks"][-1]["faults"] >= 1,
              "dump's last tick shows the fault injection")
        check(len(dump["inFlight"]) >= 1 and
              all(row["outcome"] is None for row in dump["inFlight"]),
              "dump snapshots the in-flight rows before fail-fast")
    finally:
        server.stop()
        history_service.shutdown()
        history_service.join(timeout=10)
        generation.shutdown()
        generation.join(timeout=10)

    if PROBLEMS:
        print(f"history-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("history-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
