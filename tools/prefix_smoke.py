"""CPU-backend prefix-cache + chunked-prefill smoke (CI gate 2.11).

Boots the slot engine on the tiny CPU model and proves the contracts the
radix prefix cache exists for (docs/SERVING.md "Prefix cache & chunked
prefill"):

1. **Hits are faster** — at equal token counts, a request whose prompt is
   fully cached must beat the cold-path TTFT (prefill skipped straight to
   the first uncached position), and its tokens must be IDENTICAL to the
   cold run's.
2. **Shared prefixes multiply capacity** — at EQUAL cache HBM, requests
   sharing one long system prompt admit strictly more concurrent
   sequences than PR 7's 2.5x paged-vs-contiguous gate: the shared pages
   are charged once, not per request.
3. **Chunked prefill keeps decode flat** — while a long prompt
   chunk-prefills, the running batch emits a token EVERY tick (the
   structural no-stall guarantee), and the worst inter-token gap during
   the join stays below the monolithic whole-prompt prefill stall the
   rollback engine pays for the same prompt.
4. **Zero post-warmup recompiles** — hits, misses, chunk boundaries, COW
   divergence and eviction are all traced-operand changes; the jit caches
   must not grow.
5. **The prefix metrics are scrapeable** — hits/misses/cached-pages and
   the chunk histogram land in the exposition.

Run via ``make prefix-smoke``; CI runs it after the trace smoke.
"""
from __future__ import annotations

import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tensorhive_tpu.models.transformer import PRESETS, TransformerLM  # noqa: E402
from tensorhive_tpu.observability import get_registry  # noqa: E402
from tensorhive_tpu.serving.engine import SlotEngine  # noqa: E402

MAX_LEN = 256
PAGE_SIZE = 16
#: the "one system prompt, a million users" shape: a long shared prefix
#: and a short per-user suffix
SYSTEM_TOKENS = 160
NEW_TOKENS = 6

#: scenario 2 — equal-HBM capacity. The contiguous engine gets
#: CONTIG_SLOTS x MAX_LEN cells; the prefix engine the SAME cell count as
#: pages. Each request needs ceil((161 + 6) / 16) = 11 pages, 10 of them
#: the shared prefix — so after one warming request the pool admits
#: (32 - 11) / 1 = 21 more shared-suffix requests concurrently where the
#: contiguous engine holds 2 and a prefix-less paged pool would hold 2.
CONTIG_SLOTS = 2
EQUAL_HBM_PAGES = CONTIG_SLOTS * MAX_LEN // PAGE_SIZE
FANIN = 12
GAIN_GATE = 2.5


def main() -> int:
    failures = []
    config = PRESETS["tiny"]
    params = TransformerLM.init(jax.random.PRNGKey(0), config)
    system = [(13 * j) % config.vocab_size or 1 for j in range(SYSTEM_TOKENS)]

    def drain(engine):
        while engine.has_work():
            engine.step()

    def check_recompiles(name, eng, steps0, prefills0):
        step_growth = eng.step_executable._cache_size() - steps0
        prefill_growth = eng.prefill_executable._cache_size() - prefills0
        if step_growth or prefill_growth:
            failures.append(
                f"{name}: recompiles after warmup (step +{step_growth}, "
                f"prefill +{prefill_growth}) — a start offset, chunk "
                "boundary or page assignment leaked into a static shape")

    # -- 1: hit-path TTFT < miss-path TTFT at equal tokens -----------------
    engine = SlotEngine(params, config, slots=4, max_len=MAX_LEN,
                        queue_depth=2 * FANIN, page_size=PAGE_SIZE,
                        prefill_chunk_tokens=64, speculative="off", kv_quant="off")
    engine.warmup(prompt_lens=(SYSTEM_TOKENS + 1,))
    step_execs = engine.step_executable._cache_size()
    prefill_execs = engine.prefill_executable._cache_size()

    prompt = system + [7]
    cold = engine.submit(prompt, max_new_tokens=NEW_TOKENS)
    drain(engine)
    cold_summary = cold.result(timeout_s=10)
    warm = engine.submit(prompt, max_new_tokens=NEW_TOKENS)
    drain(engine)
    warm_summary = warm.result(timeout_s=10)
    if warm_summary["tokens"] != cold_summary["tokens"]:
        failures.append("hit-path tokens differ from the cold run — the "
                        "cached pages do not hold the prefill's K/V")
    cold_ttft, warm_ttft = cold_summary["ttftS"], warm_summary["ttftS"]
    if not warm_ttft < cold_ttft:
        failures.append(
            f"hit TTFT {warm_ttft * 1e3:.1f}ms not below miss TTFT "
            f"{cold_ttft * 1e3:.1f}ms at equal tokens — prefill is not "
            "skipping the cached prefix")
    stats = engine.stats()
    if stats["prefixHits"] < 1 or stats["prefixMisses"] < 1:
        failures.append(f"hit/miss counters wrong: {stats['prefixHits']} "
                        f"hits, {stats['prefixMisses']} misses")
    # recompile check runs NOW: the jit caches are process-global, so a
    # later scenario's differently-shaped engine would inflate the delta
    check_recompiles("hit/miss engine", engine, step_execs, prefill_execs)

    # -- 2: equal-HBM concurrency through the shared prefix ----------------
    prefix_pool = SlotEngine(params, config, slots=FANIN, max_len=MAX_LEN,
                             queue_depth=2 * FANIN, page_size=PAGE_SIZE,
                             kv_pages=EQUAL_HBM_PAGES,
                             prefill_chunk_tokens=64, speculative="off", kv_quant="off")
    prefix_pool.warmup(prompt_lens=(SYSTEM_TOKENS + 1,))
    pool_step_execs = prefix_pool.step_executable._cache_size()
    pool_prefill_execs = prefix_pool.prefill_executable._cache_size()
    warmer = prefix_pool.submit(system + [3], max_new_tokens=NEW_TOKENS)
    drain(prefix_pool)
    if warmer.result(timeout_s=10)["outcome"] != "completed":
        failures.append("cache-warming request did not complete")

    fan_in = [prefix_pool.submit(system + [20 + i], max_new_tokens=NEW_TOKENS)
              for i in range(FANIN)]
    prefix_busy = 0
    while prefix_pool.has_work():
        prefix_pool.step()
        prefix_busy = max(prefix_busy, prefix_pool.stats()["slotsBusy"])
    if not all(h.result(timeout_s=10)["outcome"] == "completed"
               for h in fan_in):
        failures.append("shared-prefix fan-in: not every request completed")

    contiguous = SlotEngine(params, config, slots=CONTIG_SLOTS,
                            max_len=MAX_LEN, queue_depth=2 * FANIN,
                            paged=False, speculative="off", kv_quant="off")
    contiguous.warmup(prompt_lens=(SYSTEM_TOKENS + 1,))
    contig_handles = [contiguous.submit(system + [20 + i],
                                        max_new_tokens=NEW_TOKENS)
                      for i in range(FANIN)]
    contig_busy = 0
    while contiguous.has_work():
        contiguous.step()
        contig_busy = max(contig_busy, contiguous.stats()["slotsBusy"])
    if not all(h.result(timeout_s=10)["outcome"] == "completed"
               for h in contig_handles):
        failures.append("contiguous fan-in: not every request completed")

    gain = prefix_busy / max(1, contig_busy)
    if not gain > GAIN_GATE:
        failures.append(
            f"shared-prefix concurrency {gain:.2f}x not strictly above the "
            f"PR 7 {GAIN_GATE}x gate at equal HBM ({prefix_busy} vs "
            f"{contig_busy}) — shared pages are being charged per request")
    check_recompiles("fan-in engine", prefix_pool, pool_step_execs,
                     pool_prefill_execs)

    # -- 3: decode stays flat while a long prompt chunk-prefills -----------
    # monolithic baseline: the SAME prompt through the rollback engine —
    # its join stalls the tick by one whole-prompt prefill
    rollback = SlotEngine(params, config, slots=2, max_len=MAX_LEN,
                          queue_depth=4, page_size=PAGE_SIZE,
                          prefix_cache="off", speculative="off", kv_quant="off")
    rollback.warmup(prompt_lens=(SYSTEM_TOKENS + 1,))
    runner = rollback.submit([5, 6, 7], max_new_tokens=40)
    rollback.step()
    stamps = [time.perf_counter()]
    rollback.submit(system + [9], max_new_tokens=2)
    for _ in range(8):
        rollback.step()
        stamps.append(time.perf_counter())
    runner.cancel()
    drain(rollback)
    monolithic_stall = max(b - a for a, b in zip(stamps, stamps[1:]))

    chunked = SlotEngine(params, config, slots=2, max_len=MAX_LEN,
                         queue_depth=4, page_size=PAGE_SIZE,
                         prefill_chunk_tokens=16, speculative="off", kv_quant="off")
    chunked.warmup(prompt_lens=(SYSTEM_TOKENS + 1,))
    runner = chunked.submit([5, 6, 7], max_new_tokens=40)
    chunked.step()
    tokens_before = len(runner._request.generated)
    joiner = chunked.submit(system + [9], max_new_tokens=2)
    stamps = [time.perf_counter()]
    join_ticks = 12                   # > ceil(160 / 16) chunks
    for _ in range(join_ticks):
        chunked.step()
        stamps.append(time.perf_counter())
    ticked = len(runner._request.generated) - tokens_before
    if ticked != join_ticks:
        failures.append(
            f"running batch emitted {ticked} tokens over {join_ticks} "
            "ticks while the long prompt chunk-prefilled — chunking is "
            "stalling decode")
    chunked_worst = max(b - a for a, b in zip(stamps, stamps[1:]))
    if not chunked_worst < monolithic_stall:
        failures.append(
            f"worst inter-token gap during the chunked join "
            f"({chunked_worst * 1e3:.1f}ms) is not below the monolithic "
            f"join stall ({monolithic_stall * 1e3:.1f}ms) — the chunk "
            "budget is not bounding per-tick prefill work")
    runner.cancel()
    drain(chunked)
    if joiner.result(timeout_s=10)["outcome"] != "completed":
        failures.append("chunk-prefilled joiner did not complete")

    # -- 5: prefix metrics present in the exposition -----------------------
    rendered = get_registry().render()
    for family in ("tpuhive_generate_prefix_hits_total",
                   "tpuhive_generate_prefix_misses_total",
                   "tpuhive_generate_prefix_cached_pages",
                   "tpuhive_generate_prefill_chunks_bucket"):
        if family not in rendered:
            failures.append(f"metric missing from exposition: {family}")

    print(f"prefix-smoke: shared prefix {SYSTEM_TOKENS} tokens | "
          f"TTFT miss {cold_ttft * 1e3:.1f}ms -> hit {warm_ttft * 1e3:.1f}ms "
          f"| equal-HBM concurrency {prefix_busy} vs {contig_busy} "
          f"({gain:.2f}x > {GAIN_GATE}x) | chunked-join worst gap "
          f"{chunked_worst * 1e3:.1f}ms vs monolithic stall "
          f"{monolithic_stall * 1e3:.1f}ms | "
          f"stats={prefix_pool.stats()}")
    for failure in failures:
        print(f"prefix-smoke FAILURE: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
