"""Smoke-test the metrics exposition end to end (``make metrics-smoke``).

Boots the real WSGI app in-process on an ephemeral port (in-memory DB, no
hosts), issues one real API request so the dispatch instrumentation has
something to count, then scrapes ``/api/metrics`` over HTTP and checks the
Prometheus text format. Exit 0 = healthy.
"""
from __future__ import annotations

import os
import sys
import tempfile
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory


def main() -> int:
    from tensorhive_tpu.config import Config, set_config

    set_config(Config(config_dir=tempfile.mkdtemp(prefix="tpuhive-smoke-")))

    from tensorhive_tpu.db.engine import Engine, set_engine
    from tensorhive_tpu.db.migrations import ensure_schema

    engine = Engine(":memory:")
    ensure_schema(engine)
    set_engine(engine)

    from tensorhive_tpu.core.managers.manager import TpuHiveManager, set_manager

    set_manager(TpuHiveManager(services=[]))

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    try:
        # one real dispatched request so the scrape has populated families
        with urllib.request.urlopen(f"{base}/openapi.json", timeout=10) as resp:
            assert resp.status == 200

        # the health probes answer over real HTTP (unauthenticated)
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as resp:
            healthz_status = resp.status
            healthz_body = resp.read().decode()
        with urllib.request.urlopen(f"{base}/readyz", timeout=10) as resp:
            readyz_status = resp.status
            readyz_body = resp.read().decode()

        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            content_type = resp.headers.get("Content-Type", "")
            body = resp.read().decode()
    finally:
        server.stop()

    problems = []
    if "text/plain" not in content_type or "version=0.0.4" not in content_type:
        problems.append(f"unexpected content type: {content_type!r}")
    if "# TYPE tpuhive_api_requests_total counter" not in body:
        problems.append("request counter missing from exposition")
    if "tpuhive_api_request_seconds_bucket" not in body:
        problems.append("request latency histogram missing from exposition")
    if "tpuhive_alerts_firing{" not in body:
        problems.append("alert firing gauges missing from exposition")
    if 'tpuhive_build_info{version="' not in body:
        problems.append("build info gauge missing from exposition")
    if "tpuhive_process_uptime_seconds" not in body:
        problems.append("process self-metrics missing from exposition")
    if healthz_status != 200 or '"status": "ok"' not in healthz_body:
        problems.append(
            f"healthz not ok: {healthz_status} {healthz_body[:200]!r}")
    if readyz_status != 200 or '"ready": true' not in readyz_body:
        problems.append(
            f"readyz not ready: {readyz_status} {readyz_body[:200]!r}")
    if not body.endswith("\n"):
        problems.append("exposition must end with a newline")
    for problem in problems:
        print(f"metrics-smoke: FAIL: {problem}", file=sys.stderr)
    if problems:
        return 1
    lines = len(body.splitlines())
    print(f"metrics-smoke: OK — {lines} exposition lines from {base}/metrics")
    return 0


if __name__ == "__main__":
    sys.exit(main())
