"""Smoke-test KV-page tiering end to end (``make tier-smoke``;
docs/SERVING.md "KV-page tiering").

Boots the real daemon surface — WSGI app over a real socket, a live
GenerationService pump, in-memory DB — around a ``host_kv_bytes > 0``
engine whose page pool is sized so ONE follow-up prompt must evict the
first prompt's cached pages, then proves the tier's operational contract
over HTTP:

1. stream the probe prompt cold (tier MISS, full chunked prefill), churn
   it out of HBM with a second prompt (eviction -> demotion to host RAM),
   then stream the probe again: the host-tier HIT must emit IDENTICAL
   tokens — promotion replaces the prefill fill, never the math;
2. the host-hit's TTFT must beat the cold miss's (the ledger's
   ``ttftMs`` over HTTP): a DMA promotion plus one tail chunk is cheaper
   than recomputing every chunk — the whole point of the tier;
3. the hit's ledger row carries ``hostHitPages > 0`` and ``promoteMs``,
   the miss's carries ``hostHitPages == 0`` (tier on, nothing resident);
4. ``/api/generate/stats`` reports the ``hostKvBytes`` / ``hostPagesResident``
   / ``hostBytesUsed`` / ``hostHitRate`` block and ``/api/metrics`` exports
   the ``tpuhive_generate_host_kv_*`` counters and byte gauges;
5. ZERO post-warmup recompiles across the full demote/promote round trip —
   the copy executables are fixed-width and warmed, tier membership is
   host bookkeeping (the zero-recompile contract).

Engines run the f32 tiny config (like the unit suite). Exit 0 = healthy.
"""
from __future__ import annotations

import json
import os
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

#: 88 tokens over chunk size 8: a cold prefill pays ~11 chunk ticks while
#: a host hit promotes 10 pages by DMA and prefills ONE tail chunk — the
#: TTFT gap the smoke gates on is tick-count structural, not noise
PROMPT = [(5 * j + 3) % 250 + 1 for j in range(88)]
CHURN = [(7 * j + 11) % 250 + 1 for j in range(88)]
NEW_TOKENS = 8
PAGE_SIZE = 8
CHUNK_TOKENS = 8
#: pages_for(88 + 8) with page_size 8 — one request fills the whole pool,
#: so the churn prompt's admission MUST evict (and thereby demote) the
#: probe's cached pages
KV_PAGES = 12

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"tier-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def request(url: str, body=None, headers=None, method=None):
    """(status, text, headers) over real HTTP; >=400 is a result."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def stream(base: str, auth: dict, prompt):
    status, body, _ = request(f"{base}/generate", body={
        "promptTokens": prompt, "maxNewTokens": NEW_TOKENS,
        "temperature": 0}, headers=auth)
    check(status == 200, f"POST /generate streamed (got {status})")
    lines = [json.loads(line) for line in body.strip().splitlines()]
    done = lines[-1]
    check(done.get("outcome") == "completed",
          f"stream completed (got {done})")
    return done.get("tokens"), done.get("requestId")


def ledger_row(base: str, auth: dict, request_id: str):
    status, body, _ = request(f"{base}/admin/requests", headers=auth)
    check(status == 200, f"GET /admin/requests (got {status})")
    rows = [row for row in json.loads(body)["requests"]
            if row["requestId"] == request_id]
    check(len(rows) == 1, f"ledger row for {request_id}")
    return rows[0] if rows else {}


def main() -> int:
    import dataclasses

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tensorhive_tpu.config import Config, set_config

    config = Config(config_dir=Path("/tmp/tpuhive-tier-smoke"))
    config.api.secret_key = "tier-smoke-secret"
    config.generation.enabled = True
    config.generation.interval_s = 0.01
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine as set_db
    from tensorhive_tpu.db.migrations import ensure_schema

    engine_db = Engine(":memory:")
    ensure_schema(engine_db)
    set_db(engine_db)

    from tensorhive_tpu.db.models import User

    admin = User(username="smoke-admin", email="smoke@example.com",
                 password="SuperSecret42").save()
    admin.add_role("user")
    admin.add_role("admin")

    from tensorhive_tpu.core.services.generation import GenerationService
    from tensorhive_tpu.models.decode import _compile_seen
    from tensorhive_tpu.models.transformer import PRESETS, TransformerLM
    from tensorhive_tpu.serving.engine import SlotEngine

    f32_tiny = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32,
                                   use_flash=False, remat=False,
                                   max_seq_len=128)
    params = TransformerLM.init(jax.random.PRNGKey(0), f32_tiny)

    engine = SlotEngine(params, f32_tiny, slots=2, max_len=128,
                        queue_depth=4, page_size=PAGE_SIZE,
                        kv_pages=KV_PAGES, prefix_cache="on",
                        prefix_min_tokens=PAGE_SIZE,
                        prefill_chunk_tokens=CHUNK_TOKENS,
                        host_kv_bytes=1 << 20)
    engine.warmup(prompt_lens=(len(PROMPT),))
    compiles_after_warmup = len(_compile_seen)

    generation = GenerationService(config=config, engine=engine)
    generation.start()

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    try:
        status, body, _ = request(f"{base}/user/login", body={
            "username": "smoke-admin", "password": "SuperSecret42"})
        check(status == 200, f"admin login over HTTP (got {status})")
        auth = {"Authorization": "Bearer " + json.loads(body)["accessToken"]}

        # -- 1: cold miss, churn, host hit — identical tokens --------------
        miss_tokens, miss_id = stream(base, auth, PROMPT)
        check(isinstance(miss_tokens, list)
              and len(miss_tokens) == NEW_TOKENS,
              f"cold stream emitted {NEW_TOKENS} tokens")
        stream(base, auth, CHURN)                      # evict -> demote
        deadline = time.monotonic() + 10
        while (engine._host_store.resident_pages == 0
               and time.monotonic() < deadline):
            time.sleep(0.05)                           # lane adoption
        check(engine._host_store.resident_pages > 0,
              f"churn demoted {engine._host_store.resident_pages} pages "
              "to the host store")
        hit_tokens, hit_id = stream(base, auth, PROMPT)
        check(hit_tokens == miss_tokens,
              f"host-tier hit tokens identical to the cold miss "
              f"({hit_tokens} vs {miss_tokens})")
        check(engine.host_kv_promotions > 0,
              f"pages promoted back by DMA ({engine.host_kv_promotions})")

        # -- 2 + 3: TTFT beats the miss; the ledger tells the story --------
        miss_row = ledger_row(base, auth, miss_id)
        hit_row = ledger_row(base, auth, hit_id)
        check(miss_row.get("hostHitPages") == 0
              and miss_row.get("promoteMs") is None,
              "miss row: hostHitPages=0, promoteMs=null")
        check((hit_row.get("hostHitPages") or 0) > 0,
              f"hit row promoted {hit_row.get('hostHitPages')} pages")
        check(hit_row.get("promoteMs") is not None,
              f"hit row carries promoteMs ({hit_row.get('promoteMs')})")
        check((hit_row.get("ttftMs") or 1e9) < (miss_row.get("ttftMs")
                                                or 0),
              f"host-hit TTFT {hit_row.get('ttftMs')}ms beats the miss's "
              f"{miss_row.get('ttftMs')}ms")

        # -- 4: stats block + metric exposition ----------------------------
        status, body, _ = request(f"{base}/generate/stats", headers=auth)
        check(status == 200, f"GET /generate/stats (got {status})")
        stats = json.loads(body)
        check(stats.get("hostKvBytes") == 1 << 20,
              "stats report the host_kv_bytes budget")
        check((stats.get("hostPagesResident") or 0) >= 0
              and stats.get("hostBytesUsed") is not None,
              "stats report host store residency")
        check((stats.get("hostHitRate") or 0) > 0,
              f"stats report hostHitRate ({stats.get('hostHitRate')})")
        status, scrape, _ = request(f"{base}/metrics")
        check(status == 200, f"GET /metrics (got {status})")
        for metric in ("tpuhive_generate_host_kv_hits_total",
                       "tpuhive_generate_host_kv_misses_total",
                       "tpuhive_generate_host_kv_demotions_total",
                       "tpuhive_generate_host_kv_promotions_total",
                       "tpuhive_generate_host_kv_bytes_used",
                       "tpuhive_generate_host_kv_bytes_capacity"):
            check(metric in scrape, f"{metric} in the exposition")

        # -- 5: zero post-warmup recompiles through the round trip ---------
        check(len(_compile_seen) == compiles_after_warmup,
              "zero new executables across demote + promote "
              f"({len(_compile_seen)} vs {compiles_after_warmup} warmed)")
    finally:
        server.stop()
        generation.shutdown()
        generation.join(timeout=5)

    if PROBLEMS:
        print(f"tier-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("tier-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
