"""Multi-chip serving smoke: the sharded slot engine on 8 forced devices.

Boots the serving plane exactly as ``[generation_service] mesh_dp = 2,
mesh_tp = 2`` would — through ``build_engine`` on a virtual 8-device CPU
platform (``--xla_force_host_platform_device_count=8``, the same trick the
test suite and the MULTICHIP dryruns use) — and proves the contracts
docs/SERVING.md "Multi-chip serving" promises:

1. **Sharded == single-chip, token-identical.** The same mixed-length
   greedy workload through the 2x2-mesh engine and through the 1x1 engine
   yields identical token streams (GSPMD partitioning is a placement
   decision, never a behavior).
2. **Zero post-warmup recompiles under sharding.** Joins, leaves and page
   assignment on the dp-sharded cache must not mint new executables — the
   traced-operand discipline survives NamedShardings.
3. **Slot capacity scales with dp at equal per-chip HBM.** ``slots`` is
   per-dp-shard, so the 2x2 engine serves 2x the sequences of the
   single-chip config while each chip holds the same cache rows.
4. **1x1 is a fingerprint-identical rollback.** ``mesh_dp = mesh_tp = 1``
   builds an engine with NO mesh (same executables, same
   ``serving_*`` — not ``serving_mesh_*`` — compile fingerprints).

Run via ``make serving-mesh-smoke``; CI runs it after the serving smoke.
"""
from __future__ import annotations

import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tensorhive_tpu.config import Config  # noqa: E402
from tensorhive_tpu.core.services.generation import build_engine  # noqa: E402
from tensorhive_tpu.observability import get_registry  # noqa: E402

SLOTS_PER_SHARD = 4
NEW_TOKENS = 8
PROMPT_LENS = (12, 20, 1, 33, 12, 20, 33, 5)
MAX_LEN = 64


def serving_config(mesh_dp: int, mesh_tp: int) -> Config:
    cfg = Config(config_dir=Path("/tmp"))
    cfg.generation.enabled = True
    cfg.generation.preset = "tiny"
    cfg.generation.slots = SLOTS_PER_SHARD
    cfg.generation.max_len = MAX_LEN
    cfg.generation.mesh_dp = mesh_dp
    cfg.generation.mesh_tp = mesh_tp
    cfg.generation.queue_depth = 2 * len(PROMPT_LENS)
    cfg.generation.use_flash = False
    # legacy mesh contracts measure sharding, never speculation
    cfg.generation.speculative = "off"
    cfg.generation.kv_quant = "off"
    return cfg


def run_workload(engine):
    """Submit the mixed-length storm (more requests than one shard's slots,
    so slots are reused and pages recycled) and return every token list."""
    prompts = [[(7 * i + j) % engine.config.vocab_size or 1
                for j in range(plen)] for i, plen in enumerate(PROMPT_LENS)]
    handles = [engine.submit(prompt, max_new_tokens=NEW_TOKENS)
               for prompt in prompts]
    while engine.has_work():
        engine.step()
    return [handle.result(timeout_s=10)["tokens"] for handle in handles]


def main() -> int:
    failures = []

    single = build_engine(serving_config(1, 1))
    if single.mesh is not None or single.mesh_shape != "1x1":
        failures.append("1x1 config built a mesh engine — rollback broken")
    if single._fingerprint_fn("serving_paged_step") != "serving_paged_step":
        failures.append("1x1 engine mints serving_mesh_* fingerprints — "
                        "rollback is not fingerprint-identical")
    single_tokens = run_workload(single)

    meshed = build_engine(serving_config(2, 2))
    stats = meshed.stats()
    if stats["meshShape"] != "2x2" or stats["numDevices"] != 4:
        failures.append(f"mesh stats wrong: {stats['meshShape']} / "
                        f"{stats['numDevices']} devices")
    if meshed.capacity != 2 * single.capacity:
        failures.append(
            f"dp=2 capacity {meshed.capacity} != 2x single-chip "
            f"{single.capacity} — the slot pool is not scaling with dp")
    # equal per-chip HBM: the dp-sharded page pool holds the single-chip
    # engine's rows PER SHARD
    if meshed._pool.num_pages != 2 * single._pool.num_pages:
        failures.append(
            f"dp=2 page pool {meshed._pool.num_pages} != 2x single-chip "
            f"{single._pool.num_pages} — per-chip HBM drifted")
    if meshed._cache.k.sharding.spec != jax.sharding.PartitionSpec(
            None, "dp", None, "tp"):
        failures.append(
            f"cache sharding {meshed._cache.k.sharding.spec} is not "
            "(pages over dp, kv_heads over tp)")

    step_execs = meshed.step_executable._cache_size()
    prefill_execs = meshed.prefill_executable._cache_size()
    mesh_tokens = run_workload(meshed)
    step_growth = meshed.step_executable._cache_size() - step_execs
    prefill_growth = meshed.prefill_executable._cache_size() - prefill_execs
    if step_growth or prefill_growth:
        failures.append(
            f"recompiles on the sharded engine: step +{step_growth}, "
            f"prefill +{prefill_growth} — a sharding or page table leaked "
            "into a static shape")

    if mesh_tokens != single_tokens:
        diffs = sum(1 for a, b in zip(mesh_tokens, single_tokens) if a != b)
        failures.append(
            f"sharded tokens differ from single-chip on {diffs}/"
            f"{len(single_tokens)} requests — GSPMD changed behavior")

    rendered = get_registry().render()
    if "tpuhive_generate_mesh_devices 4" not in rendered:
        failures.append("tpuhive_generate_mesh_devices gauge missing or "
                        "wrong in the exposition")

    print(f"serving-mesh-smoke: {len(PROMPT_LENS)} requests x {NEW_TOKENS} "
          f"tokens | 1x1 capacity {single.capacity} vs 2x2 capacity "
          f"{meshed.capacity} on {jax.device_count()} forced devices | "
          f"cache {meshed._cache.k.sharding.spec} | "
          f"step_growth={step_growth} prefill_growth={prefill_growth} | "
          f"token-identical={mesh_tokens == single_tokens}")
    for failure in failures:
        print(f"serving-mesh-smoke FAILURE: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
