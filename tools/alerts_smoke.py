"""Smoke-test the alerting + readiness loop end to end (``make alerts-smoke``).

Boots the real WSGI app in-process with one registered (deliberately dead)
daemon service and walks the whole measured→actionable loop over real HTTP:

1. dead service → ``GET /api/readyz`` is 503 naming the component, the
   ``service_down`` rule fires exactly once through the AlertingService
   fan-out, and the scrape shows ``tpuhive_alerts_firing{...} 1``;
2. service started → readiness flips to 200, the alert resolves exactly
   once, and the gauge drops to 0.

Exit 0 = healthy.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"alerts-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def fetch(url: str):
    """(status, body) — urllib raises on >=400, readiness 503 is a result."""
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode()


def main() -> int:
    from tensorhive_tpu.config import Config, set_config

    set_config(Config(config_dir=tempfile.mkdtemp(prefix="tpuhive-smoke-")))

    from tensorhive_tpu.db.engine import Engine, set_engine
    from tensorhive_tpu.db.migrations import ensure_schema

    engine = Engine(":memory:")
    ensure_schema(engine)
    set_engine(engine)

    from tensorhive_tpu.core.managers.manager import TpuHiveManager, set_manager
    from tensorhive_tpu.core.services.alerting import AlertingService, LogSink
    from tensorhive_tpu.core.services.base import Service
    from tensorhive_tpu.observability.alerts import get_alert_engine

    class SmokeWorker(Service):
        def do_run(self) -> None:
            pass

    worker = SmokeWorker(interval_s=0.05)
    manager = TpuHiveManager(services=[worker])
    manager.configure_services_from_config()
    set_manager(manager)

    notifications = []

    class RecordingSink(LogSink):
        name = "recording"

        def notify(self, event: dict) -> None:
            notifications.append(event)
            super().notify(event)

    alerting = AlertingService(engine=get_alert_engine(),
                               sinks=[RecordingSink()])

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    try:
        # -- phase 1: the registered worker is dead (never started) --------
        status, body = fetch(f"{base}/readyz")
        doc = json.loads(body)
        check(status == 503, f"readyz is 503 while the service is dead "
                             f"(got {status})")
        check(any(c["component"] == "service:SmokeWorker" and not c["ok"]
                  for c in doc.get("components", [])),
              "readyz names the dead component")
        check(any("service:SmokeWorker" in reason
                  for reason in doc.get("reasons", [])),
              f"readyz reason list names the service: {doc.get('reasons')}")

        alerting.do_run()                              # one evaluation tick
        fired = [e for e in notifications
                 if e["rule"] == "service_down" and e["to"] == "firing"]
        check(len(fired) == 1,
              f"service_down fired exactly once (got {len(fired)})")
        alerting.do_run()                              # re-evaluate: no dupes
        fired = [e for e in notifications
                 if e["rule"] == "service_down" and e["to"] == "firing"]
        check(len(fired) == 1, "repeated evaluation sends no duplicate")

        _, scrape = fetch(f"{base}/metrics")
        check('tpuhive_alerts_firing{rule="service_down",'
              'severity="critical"} 1' in scrape,
              "firing state exported on /api/metrics")

        # -- phase 2: service comes up, alert resolves ---------------------
        worker.start()
        deadline = time.time() + 5
        while worker.ticks_completed < 1 and time.time() < deadline:
            time.sleep(0.01)
        check(worker.ticks_completed >= 1, "smoke worker ticked")

        status, body = fetch(f"{base}/readyz")
        doc = json.loads(body)
        check(status == 200 and doc.get("ready") is True,
              f"readyz back to 200 once the service is alive (got {status})")
        check(all(c["ok"] for c in doc.get("components", [])),
              "all components ok in the ready payload")

        alerting.do_run()
        resolved = [e for e in notifications
                    if e["rule"] == "service_down" and e["to"] == "resolved"]
        check(len(resolved) == 1,
              f"service_down resolved exactly once (got {len(resolved)})")

        _, scrape = fetch(f"{base}/metrics")
        check('tpuhive_alerts_firing{rule="service_down",'
              'severity="critical"} 0' in scrape,
              "resolved state exported on /api/metrics")

        status, _ = fetch(f"{base}/healthz")
        check(status == 200, "healthz stays 200 throughout")
    finally:
        worker.shutdown()
        worker.join(timeout=5)
        server.stop()

    if PROBLEMS:
        print(f"alerts-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("alerts-smoke: OK — dead service detected, alert fired and "
          "resolved, readiness flipped 503→200")
    return 0


if __name__ == "__main__":
    sys.exit(main())
