"""Dependency-free static gate (reference CI parity: mypy + flake8 on every
push, /root/reference/.circleci/config.yml:33-38 via SURVEY.md §4).

This image ships neither mypy nor ruff and has no network, so the gate that
ALWAYS runs is this stdlib checker; ``make check`` additionally invokes mypy
and ruff (configured in pyproject.toml) when they are installed. Checks:

* every source file parses (syntax gate = flake8's E9 class),
* no unused imports (flake8 F401) — the highest-signal pyflakes rule,
* no obvious undefined names in function bodies for a conservative subset
  (flake8 F821-lite): names read in a module that are neither defined
  anywhere in it, imported, builtins, nor comprehension/loop targets.

Exit 0 = clean. Run: ``python tools/lint.py [paths...]``
"""
from __future__ import annotations

import ast
import builtins
import sys
from pathlib import Path

DEFAULT_TARGETS = ("tensorhive_tpu", "tests", "examples", "tools", "bench.py",
                   "__graft_entry__.py")

#: names every module may reference without defining (dunders + pytest)
IMPLICIT = {"__file__", "__name__", "__doc__", "__package__", "__spec__",
            "__builtins__", "__debug__", "__class__"}


def iter_sources(args: list) -> list:
    root = Path(__file__).resolve().parent.parent
    targets = [root / t for t in (args or DEFAULT_TARGETS)]
    files = []
    for target in targets:
        if target.is_dir():
            files.extend(sorted(target.rglob("*.py")))
        elif target.suffix == ".py":
            files.append(target)
    return files


class NameCollector(ast.NodeVisitor):
    """All identifiers read or written anywhere in the module."""

    def __init__(self) -> None:
        self.read = set()
        self.bound = set()

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.read.add(node.id)
        else:
            self.bound.add(node.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node) -> None:
        self.bound.add(node.name)
        for arg in ([*node.args.posonlyargs, *node.args.args,
                     *node.args.kwonlyargs]
                    + ([node.args.vararg] if node.args.vararg else [])
                    + ([node.args.kwarg] if node.args.kwarg else [])):
            self.bound.add(arg.arg)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self.bound.update(node.names)

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self.bound.update(node.names)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        for arg in ([*node.args.posonlyargs, *node.args.args,
                     *node.args.kwonlyargs]
                    + ([node.args.vararg] if node.args.vararg else [])
                    + ([node.args.kwarg] if node.args.kwarg else [])):
            self.bound.add(arg.arg)
        self.generic_visit(node)


def imported_names(tree: ast.AST):
    """(bound name, lineno, display) for every import binding."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                out.append((bound, node.lineno, alias.name))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                out.append((bound, node.lineno, alias.name))
    return out


def string_literals(tree: ast.AST):
    """String constants — names referenced in __all__, TYPE_CHECKING hints,
    or docstring doctests count as uses (conservative)."""
    found = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for token in node.value.replace(".", " ").replace(",", " ").split():
                if token.isidentifier():
                    found.add(token)
    return found


BUILTIN_NAMES = set(dir(builtins)) | IMPLICIT


def check_file(path: Path) -> list:
    problems = []
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]

    lines = source.splitlines()
    collector = NameCollector()
    collector.visit(tree)
    strings = string_literals(tree)
    imports = imported_names(tree)
    imported = {bound for bound, _, _ in imports}
    has_star = any(
        isinstance(node, ast.ImportFrom) and any(a.name == "*" for a in node.names)
        for node in ast.walk(tree))

    init_reexport = path.name == "__init__.py"
    for bound, lineno, display in imports:
        if init_reexport:
            continue        # __init__ imports are the package's public API
        line = lines[lineno - 1] if lineno - 1 < len(lines) else ""
        if "noqa" in line:
            continue
        if bound not in collector.read and bound not in strings:
            problems.append(f"{path}:{lineno}: unused import: {display}")

    # module-flat undefined-name pass (F821-lite): a name read anywhere but
    # bound nowhere in the module, not imported, and not a builtin is a
    # NameError waiting for its code path. Module-flat = zero scope-model
    # false positives (an inner binding whitelists the name file-wide).
    if not has_star:
        known = collector.bound | imported | BUILTIN_NAMES
        for name in sorted(collector.read - known):
            problems.append(f"{path}: undefined name: {name}")
    return problems


def main() -> int:
    files = iter_sources(sys.argv[1:])
    if not files:
        print("lint: no python sources found", file=sys.stderr)
        return 1
    problems = []
    for path in files:
        problems.extend(check_file(path))
    for problem in problems:
        print(problem)
    print(f"lint: {len(files)} files, {len(problems)} problems",
          file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
