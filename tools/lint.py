"""Dependency-free static gate — alias for the tools/analysis package.

Historically this file WAS the gate (syntax + unused imports + F821-lite,
reference CI parity: mypy + flake8 on every push, SURVEY.md §4). Those checks
now live in ``tools/analysis`` as registered passes (TH-SYNTAX / TH-F401 /
TH-F821) next to the concurrency / exception-hygiene / blocking-call /
JAX-host-sync passes; this entry point keeps every existing invocation
(``make lint``, CI, tests/unit/test_lint_gate.py) running the full analyzer.

Exit 0 = clean. Run: ``python tools/lint.py [paths...]``
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.analysis.engine import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(prog="lint"))
