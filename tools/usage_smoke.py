"""Smoke-test the tenant attribution plane end to end
(``make usage-smoke``; docs/OBSERVABILITY.md "Tenant accounting").

Boots the real daemon surface — WSGI app over a real socket, a live
GenerationService pump metering into the singleton :class:`TenantMeter`,
in-memory DB — then has TWO tenants stream ``POST /api/generate``
concurrently and proves the accounting contract over HTTP:

1. ``GET /api/admin/usage`` attributes device-seconds to both tenants,
   the per-tenant ``share`` fractions sum to 1.0 (attribution conserves:
   every metered busy slot-second lands on exactly one tenant), and the
   heavier tenant's share is the larger one;
2. ``?user=`` narrows the usage rollup to one tenant's row, and the
   same filter on ``GET /api/admin/requests`` isolates that tenant's
   ledger rows — each carrying the PR 19 ``deviceSeconds`` attribution;
3. the ``/api/metrics`` scrape stays cardinality-bounded: at most
   ``top_k_tenants + 1`` ``tpuhive_tenant_device_seconds_total``
   children no matter who talked to the engine;
4. the metering hooks add ZERO post-warmup recompiles — the prefill and
   step executable caches are byte-for-byte the warmup set after all
   the multi-tenant traffic.

Engines run the f32 tiny config (like the unit suite). Exit 0 = healthy.
"""
from __future__ import annotations

import dataclasses
import json
import os
import shutil
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

PROMPT = [3, 4, 5, 6, 7, 8, 9, 10]
NEW_TOKENS = 8
TOP_K = 4
HEAVY_STREAMS = 3                                     # alice's request count

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"usage-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def request(url: str, body=None, headers=None, method=None):
    """(status, text, headers) over real HTTP; >=400 is a result."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def stream_request(base: str, auth: dict, max_new: int):
    """Stream one generate request; returns the parsed NDJSON lines."""
    req = urllib.request.Request(
        f"{base}/generate",
        data=json.dumps({"promptTokens": PROMPT, "maxNewTokens": max_new,
                         "temperature": 0}).encode(),
        headers={"Content-Type": "application/json", **auth})
    lines = []
    with urllib.request.urlopen(req, timeout=60) as resp:
        while True:
            raw = resp.readline()
            if not raw:
                break
            lines.append(json.loads(raw))
    return lines


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tensorhive_tpu.config import Config, set_config

    config_dir = Path("/tmp/tpuhive-usage-smoke")
    shutil.rmtree(config_dir, ignore_errors=True)
    config = Config(config_dir=config_dir)
    config.api.secret_key = "usage-smoke-secret"
    config.generation.enabled = True
    config.generation.interval_s = 0.01
    config.generation.transient_backoff_s = 0.0
    config.generation.require_restriction = False     # tenants need no
    config.accounting.enabled = True                  # reservation here
    config.accounting.top_k_tenants = TOP_K
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine as set_db
    from tensorhive_tpu.db.migrations import ensure_schema

    engine_db = Engine(":memory:")
    ensure_schema(engine_db)
    set_db(engine_db)

    from tensorhive_tpu.db.models import User

    admin = User(username="smoke-admin", email="smoke@example.com",
                 password="SuperSecret42").save()
    admin.add_role("user")
    admin.add_role("admin")
    alice = User(username="smoke-alice", email="alice@example.com",
                 password="SuperSecret42").save()
    alice.add_role("user")
    bob = User(username="smoke-bob", email="bob@example.com",
               password="SuperSecret42").save()
    bob.add_role("user")
    alice_key, bob_key = str(alice.id), str(bob.id)

    from tensorhive_tpu import serving
    from tensorhive_tpu.core.services.generation import GenerationService
    from tensorhive_tpu.models.transformer import PRESETS, TransformerLM
    from tensorhive_tpu.observability.accounting import get_tenant_meter
    from tensorhive_tpu.serving.engine import SlotEngine

    f32_tiny = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32,
                                   use_flash=False, remat=False,
                                   max_seq_len=128)
    params = TransformerLM.init(jax.random.PRNGKey(0), f32_tiny)

    print(f"usage-smoke: top_k_tenants={TOP_K} "
          f"heavy_streams={HEAVY_STREAMS}")

    def factory():
        engine = SlotEngine(params, f32_tiny, slots=2, max_len=96,
                            queue_depth=8, kv_quant="off",
                            tenant_meter=get_tenant_meter())
        engine.warmup(prompt_lens=(len(PROMPT),))
        return engine

    generation = GenerationService(config=config, engine=factory(),
                                   engine_factory=factory)
    generation.start()

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    try:
        def login(username):
            status, body, _ = request(f"{base}/user/login", body={
                "username": username, "password": "SuperSecret42"})
            check(status == 200, f"{username} login over HTTP (got {status})")
            return {"Authorization":
                    "Bearer " + json.loads(body)["accessToken"]}

        admin_auth = login("smoke-admin")
        alice_auth = login("smoke-alice")
        bob_auth = login("smoke-bob")

        live = serving.get_engine()
        check(live is not None, "serving engine is up")
        prefill_cache = live.prefill_executable._cache_size()
        step_cache = live.step_executable._cache_size()

        # -- 1: two tenants stream concurrently, alice 3x heavier ---------
        outcomes = []

        def run_stream(auth):
            lines = stream_request(base, auth, NEW_TOKENS)
            outcomes.append(lines[-1].get("outcome"))

        threads = [threading.Thread(target=run_stream, args=(alice_auth,))
                   for _ in range(HEAVY_STREAMS)]
        threads.append(threading.Thread(target=run_stream, args=(bob_auth,)))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        check(outcomes.count("completed") == HEAVY_STREAMS + 1,
              f"all {HEAVY_STREAMS + 1} concurrent streams completed "
              f"({outcomes})")
        # one idle pump pass meters the final busy interval
        time.sleep(0.1)

        status, body, _ = request(f"{base}/admin/usage",
                                  headers=admin_auth)
        check(status == 200, f"GET /api/admin/usage answers (got {status})")
        usage = json.loads(body)
        rows = {row["tenant"]: row for row in usage["tenants"]}
        check(alice_key in rows and bob_key in rows,
              f"both tenants attributed (got {sorted(rows)})")
        check(all(row["deviceSeconds"] > 0 for row in rows.values()),
              "both tenants hold positive device-seconds")
        share_sum = sum(row["share"] for row in usage["tenants"])
        check(abs(share_sum - 1.0) < 1e-6,
              f"shares sum to 1.0 — attribution conserves "
              f"(got {share_sum:.9f})")
        check(rows[alice_key]["deviceSeconds"] >
              rows[bob_key]["deviceSeconds"],
              f"the {HEAVY_STREAMS}-stream tenant out-charges the "
              f"1-stream tenant "
              f"(alice={rows[alice_key]['deviceSeconds']:.4f} "
              f"bob={rows[bob_key]['deviceSeconds']:.4f})")
        check(rows[alice_key]["prefillTokens"] ==
              HEAVY_STREAMS * len(PROMPT),
              f"alice's prefill tokens counted exactly "
              f"(got {rows[alice_key]['prefillTokens']}, "
              f"want {HEAVY_STREAMS * len(PROMPT)})")

        # -- 2: ?user= narrows usage AND the request ledger ---------------
        status, body, _ = request(f"{base}/admin/usage?user={bob_key}",
                                  headers=admin_auth)
        narrowed = json.loads(body)
        check(status == 200 and
              [row["tenant"] for row in narrowed["tenants"]] == [bob_key],
              f"?user= keeps exactly bob's usage row "
              f"(got {[r['tenant'] for r in narrowed.get('tenants', [])]})")

        status, body, _ = request(
            f"{base}/admin/requests?user={alice_key}", headers=admin_auth)
        ledger_rows = json.loads(body)["requests"]
        check(status == 200 and len(ledger_rows) == HEAVY_STREAMS and
              all(row["userKey"] == alice_key for row in ledger_rows),
              f"?user= isolates alice's {HEAVY_STREAMS} ledger rows "
              f"(got {len(ledger_rows)})")
        check(all(row["deviceSeconds"] > 0 for row in ledger_rows),
              "every ledger row carries its device-seconds attribution")

        # -- 3: scrape cardinality stays <= K+1 ---------------------------
        status, scrape, _ = request(f"{base}/metrics")
        device_lines = [line for line in scrape.splitlines() if
                        line.startswith("tpuhive_tenant_device_seconds"
                                        "_total{")]
        check(status == 200 and
              2 <= len(device_lines) <= TOP_K + 1,
              f"tenant device-seconds scrape bounded to K+1={TOP_K + 1} "
              f"children (got {len(device_lines)})")

        # -- 4: metering added zero post-warmup recompiles ----------------
        check(live.prefill_executable._cache_size() == prefill_cache and
              live.step_executable._cache_size() == step_cache,
              f"zero post-warmup recompiles with the meter on "
              f"(prefill {prefill_cache}->"
              f"{live.prefill_executable._cache_size()}, "
              f"step {step_cache}->{live.step_executable._cache_size()})")
    finally:
        server.stop()
        generation.shutdown()
        generation.join(timeout=10)

    if PROBLEMS:
        print(f"usage-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("usage-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
