"""CPU-backend serving smoke: continuous batching end to end.

Boots the slot engine on the tiny CPU model and proves the four contracts
the serving layer exists for (docs/SERVING.md):

1. **Liveness under concurrency** — >= 8 mixed-length requests (greedy and
   sampled) join and leave one running batch and ALL complete with the
   right token counts.
2. **Zero decode recompiles after warmup** — the step/prefill executable
   counts must not grow while mixed-length traffic joins mid-batch (the
   whole point of traced per-slot state + bucketed prefill).
3. **Batching is worth it** — batched throughput through the engine must
   beat the serial one-request-at-a-time path through the SAME engine by
   >= 2x (the continuous-batching claim, measured not asserted).
4. **Admission control sheds load** — with the queue full, exactly one
   extra submit is rejected (the API layer's 429) and the queue/slot
   metrics are present in the exposition.

Run via ``make serving-smoke``; CI runs it after the chaos gate so a
serving regression fails before the full suite spins up.
"""
from __future__ import annotations

import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402

# the axon TPU plugin overrides the env var; pin through the config API
# (same discipline as tests/conftest.py and bench.probe_backend)
jax.config.update("jax_platforms", "cpu")

from tensorhive_tpu.models.transformer import PRESETS, TransformerLM  # noqa: E402
from tensorhive_tpu.observability import get_registry  # noqa: E402
from tensorhive_tpu.serving import QueueFullError  # noqa: E402
from tensorhive_tpu.serving.engine import (  # noqa: E402
    SlotEngine,
    _serving_prefill,
    _serving_step,
)

SLOTS = 8
NEW_TOKENS = 12
#: mixed on purpose: 20/28 share prefill bucket 32, 40/56 share 64, and the
#: single-token prompt exercises the no-prefill join
PROMPT_LENS = (20, 28, 40, 56, 1, 20, 40, 56)


def main() -> int:
    failures = []
    config = PRESETS["tiny"]
    params = TransformerLM.init(jax.random.PRNGKey(0), config)
    engine = SlotEngine(params, config, slots=SLOTS, max_len=128,
                        queue_depth=SLOTS, max_new_tokens_cap=64)
    engine.warmup(prompt_lens=PROMPT_LENS)

    def prompts():
        return [[(7 * i + j) % config.vocab_size or 1 for j in range(plen)]
                for i, plen in enumerate(PROMPT_LENS)]

    def drain():
        while engine.has_work():
            engine.step()

    # -- serial baseline: one request at a time through the same engine ----
    started = time.perf_counter()
    for index, prompt in enumerate(prompts()):
        engine.submit(prompt, max_new_tokens=NEW_TOKENS,
                      temperature=0.0 if index % 2 == 0 else 0.8)
        drain()
    serial_s = time.perf_counter() - started

    # -- batched storm: everyone joins/leaves one running batch ------------
    step_execs = _serving_step._cache_size()
    prefill_execs = _serving_prefill._cache_size()
    started = time.perf_counter()
    handles = [engine.submit(prompt, max_new_tokens=NEW_TOKENS,
                             temperature=0.0 if index % 2 == 0 else 0.8)
               for index, prompt in enumerate(prompts())]
    drain()
    batched_s = time.perf_counter() - started

    for plen, handle in zip(PROMPT_LENS, handles):
        summary = handle.result(timeout_s=5)
        if summary["outcome"] != "completed":
            failures.append(f"P={plen}: outcome {summary['outcome']}")
        if len(summary["tokens"]) != NEW_TOKENS:
            failures.append(
                f"P={plen}: {len(summary['tokens'])} tokens, "
                f"wanted {NEW_TOKENS}")

    step_growth = _serving_step._cache_size() - step_execs
    prefill_growth = _serving_prefill._cache_size() - prefill_execs
    if step_growth or prefill_growth:
        failures.append(
            f"recompiles after warmup: step +{step_growth}, "
            f"prefill +{prefill_growth} — per-slot state leaked into a "
            "static shape")

    speedup = serial_s / batched_s
    if speedup < 2.0:
        failures.append(
            f"batched speedup {speedup:.2f}x < 2x over the serial "
            "single-request path")

    # -- admission control: queue full must reject exactly once ------------
    parked = [engine.submit([1, 2, 3], max_new_tokens=NEW_TOKENS)
              for _ in range(engine.queue_depth)]
    rejections = 0
    try:
        engine.submit([1, 2, 3], max_new_tokens=NEW_TOKENS)
    except QueueFullError:
        rejections = 1
    if rejections != 1:
        failures.append("queue-full submit was admitted — admission "
                        "control is not bounding the queue")
    drain()
    for handle in parked:
        if handle.result(timeout_s=5)["outcome"] != "completed":
            failures.append("parked request did not complete after drain")

    # -- queue/SLO metrics present in the exposition ------------------------
    rendered = get_registry().render()
    for family in ("tpuhive_generate_queue_depth",
                   "tpuhive_generate_slots_busy",
                   "tpuhive_generate_ttft_seconds",
                   "tpuhive_generate_batch_efficiency",
                   'tpuhive_generate_requests_total{outcome="rejected_queue"}'):
        if family not in rendered:
            failures.append(f"metric missing from exposition: {family}")

    total = len(PROMPT_LENS) * NEW_TOKENS
    print(f"serving-smoke: {len(PROMPT_LENS)} requests x {NEW_TOKENS} tokens "
          f"on {SLOTS} slots | serial {total / serial_s:.1f} tok/s, "
          f"batched {total / batched_s:.1f} tok/s ({speedup:.2f}x) | "
          f"step_execs={_serving_step._cache_size()} "
          f"prefill_execs={_serving_prefill._cache_size()} | "
          f"stats={engine.stats()}")
    for failure in failures:
        print(f"serving-smoke FAILURE: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
