"""CPU-backend serving smoke: continuous batching + paged KV end to end.

Boots the slot engine on the tiny CPU model and proves the contracts the
serving layer exists for (docs/SERVING.md):

1. **Liveness under concurrency** — >= 8 mixed-length requests (greedy and
   sampled) join and leave one running batch and ALL complete with the
   right token counts.
2. **Zero decode recompiles after warmup** — the step/prefill executable
   counts must not grow while mixed-length traffic joins mid-batch and
   pages are assigned/recycled (the whole point of traced per-slot state,
   traced page tables and bucketed prefill).
3. **Batching is worth it** — batched throughput through the engine must
   beat the serial one-request-at-a-time path through the SAME engine by
   >= 2x (the continuous-batching claim, measured not asserted).
4. **Admission control sheds load** — with the queue full, exactly one
   extra submit is rejected (the API layer's 429) and the queue/slot/page
   metrics are present in the exposition.
5. **Paging decouples capacity from context length** — at EQUAL cache HBM,
   a paged engine admits >= 1.5x more concurrent sequences than the
   contiguous engine when the summed requested context exceeds what the
   contiguous layout can hold, all of them complete, and none of it
   recompiles anything. The paged engine runs with the FUSED KERNEL
   dispatch active (``paged_kernel="on"`` — pallas interpret mode on this
   CPU backend), proving the kernel preserves the traced-page-table
   property: page assignment churns through the whole over-commit drain
   with zero post-warmup recompiles.

Run via ``make serving-smoke``; CI runs it after the chaos gate so a
serving regression fails before the full suite spins up.
"""
from __future__ import annotations

import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402

# the axon TPU plugin overrides the env var; pin through the config API
# (same discipline as tests/conftest.py and bench.probe_backend)
jax.config.update("jax_platforms", "cpu")

from tensorhive_tpu.models.transformer import PRESETS, TransformerLM  # noqa: E402
from tensorhive_tpu.observability import get_registry  # noqa: E402
from tensorhive_tpu.serving import QueueFullError  # noqa: E402
from tensorhive_tpu.serving.engine import SlotEngine  # noqa: E402

SLOTS = 8
NEW_TOKENS = 12
#: mixed on purpose: 20/28 share prefill bucket 32, 40/56 share 64, and the
#: single-token prompt exercises the no-prefill join
PROMPT_LENS = (20, 28, 40, 56, 1, 20, 40, 56)

#: scenario 5 — equal-HBM capacity comparison. The contiguous engine gets
#: CONTIG_SLOTS x MAX_LEN cache cells; the paged engine gets the SAME cell
#: count as pages (OVERCOMMIT_PAGES x PAGE_SIZE) spread over more slots.
#: Each long request needs ceil((33 + 7) / 16) = 3 pages, so the summed
#: requested context (8 x 40 = 320) exceeds the 256-cell HBM budget and
#: NEITHER engine can hold all 8 at once — the paged one just holds 2.5x
#: more (5 vs 2) because slots no longer reserve max_len upfront.
MAX_LEN = 128
CONTIG_SLOTS = 2
PAGE_SIZE = 16
OVERCOMMIT_PAGES = CONTIG_SLOTS * MAX_LEN // PAGE_SIZE      # equal HBM
LONG_PROMPT, LONG_NEW, LONG_REQUESTS = 33, 7, 8


def drain_tracking_busy(engine) -> int:
    """Drain the engine, returning the max concurrently-busy slot count
    observed — the 'concurrent admitted sequences' number of scenario 5."""
    max_busy = 0
    while engine.has_work():
        engine.step()
        max_busy = max(max_busy, engine.stats()["slotsBusy"])
    return max_busy


def main() -> int:
    failures = []
    config = PRESETS["tiny"]
    params = TransformerLM.init(jax.random.PRNGKey(0), config)
    # prefix_cache off HERE on purpose: the serial phase runs the same
    # prompts the batched storm replays, and cache hits would inflate the
    # batched-vs-serial ratio into a caching number — tools/prefix_smoke.py
    # is the gate for the prefix-cache story (scenario 5 below keeps the
    # default-on path, exercising tree retention + eviction under the
    # kernel dispatch with distinct prompts)
    engine = SlotEngine(params, config, slots=SLOTS, max_len=MAX_LEN,
                        queue_depth=SLOTS, max_new_tokens_cap=64,
                        prefix_cache="off", speculative="off", kv_quant="off")
    engine.warmup(prompt_lens=PROMPT_LENS)

    def prompts():
        return [[(7 * i + j) % config.vocab_size or 1 for j in range(plen)]
                for i, plen in enumerate(PROMPT_LENS)]

    def drain():
        while engine.has_work():
            engine.step()

    # -- serial baseline: one request at a time through the same engine ----
    started = time.perf_counter()
    for index, prompt in enumerate(prompts()):
        engine.submit(prompt, max_new_tokens=NEW_TOKENS,
                      temperature=0.0 if index % 2 == 0 else 0.8)
        drain()
    serial_s = time.perf_counter() - started

    # -- batched storm: everyone joins/leaves one running batch ------------
    step_execs = engine.step_executable._cache_size()
    prefill_execs = engine.prefill_executable._cache_size()
    started = time.perf_counter()
    handles = [engine.submit(prompt, max_new_tokens=NEW_TOKENS,
                             temperature=0.0 if index % 2 == 0 else 0.8)
               for index, prompt in enumerate(prompts())]
    drain()
    batched_s = time.perf_counter() - started

    for plen, handle in zip(PROMPT_LENS, handles):
        summary = handle.result(timeout_s=5)
        if summary["outcome"] != "completed":
            failures.append(f"P={plen}: outcome {summary['outcome']}")
        if len(summary["tokens"]) != NEW_TOKENS:
            failures.append(
                f"P={plen}: {len(summary['tokens'])} tokens, "
                f"wanted {NEW_TOKENS}")

    step_growth = engine.step_executable._cache_size() - step_execs
    prefill_growth = engine.prefill_executable._cache_size() - prefill_execs
    if step_growth or prefill_growth:
        failures.append(
            f"recompiles after warmup: step +{step_growth}, "
            f"prefill +{prefill_growth} — per-slot state or a page table "
            "leaked into a static shape")

    speedup = serial_s / batched_s
    if speedup < 2.0:
        failures.append(
            f"batched speedup {speedup:.2f}x < 2x over the serial "
            "single-request path")

    # -- admission control: queue full must reject exactly once ------------
    parked = [engine.submit([1, 2, 3], max_new_tokens=NEW_TOKENS)
              for _ in range(engine.queue_depth)]
    rejections = 0
    try:
        engine.submit([1, 2, 3], max_new_tokens=NEW_TOKENS)
    except QueueFullError:
        rejections = 1
    if rejections != 1:
        failures.append("queue-full submit was admitted — admission "
                        "control is not bounding the queue")
    drain()
    for handle in parked:
        if handle.result(timeout_s=5)["outcome"] != "completed":
            failures.append("parked request did not complete after drain")

    # -- paged vs contiguous at EQUAL HBM: long-context over-commit --------
    def long_prompts():
        return [[(5 * i + j) % config.vocab_size or 1
                 for j in range(LONG_PROMPT)] for i in range(LONG_REQUESTS)]

    requested = LONG_REQUESTS * (LONG_PROMPT + LONG_NEW)
    hbm_cells = OVERCOMMIT_PAGES * PAGE_SIZE
    assert requested > hbm_cells, "scenario must over-commit the HBM budget"

    paged = SlotEngine(params, config, slots=SLOTS, max_len=MAX_LEN,
                       queue_depth=LONG_REQUESTS, paged=True,
                       page_size=PAGE_SIZE, kv_pages=OVERCOMMIT_PAGES,
                       paged_kernel="on", speculative="off", kv_quant="off")
    if paged.stats()["pagedKernel"] != "pallas":
        failures.append("paged_kernel='on' did not dispatch the pallas "
                        "kernel — scenario 5 must exercise the fused path")
    paged.warmup(prompt_lens=(LONG_PROMPT,))
    paged_step_execs = paged.step_executable._cache_size()
    paged_prefill_execs = paged.prefill_executable._cache_size()
    paged_handles = [paged.submit(prompt, max_new_tokens=LONG_NEW)
                     for prompt in long_prompts()]
    paged_busy = drain_tracking_busy(paged)
    if not all(h.result(timeout_s=5)["outcome"] == "completed"
               for h in paged_handles):
        failures.append("paged over-commit: not every request completed")
    if (paged.step_executable._cache_size() != paged_step_execs
            or paged.prefill_executable._cache_size()
            != paged_prefill_execs):
        failures.append("paged over-commit (kernel dispatch): page "
                        "assignment recompiled an executable — the page "
                        "table leaked into a kernel shape")

    contiguous = SlotEngine(params, config, slots=CONTIG_SLOTS,
                            max_len=MAX_LEN, queue_depth=LONG_REQUESTS,
                            paged=False, speculative="off", kv_quant="off")
    contiguous.warmup(prompt_lens=(LONG_PROMPT,))
    contiguous_handles = [contiguous.submit(prompt,
                                            max_new_tokens=LONG_NEW)
                          for prompt in long_prompts()]
    contiguous_busy = drain_tracking_busy(contiguous)
    if not all(h.result(timeout_s=5)["outcome"] == "completed"
               for h in contiguous_handles):
        failures.append("contiguous over-commit: not every request "
                        "completed")

    concurrency_gain = paged_busy / max(1, contiguous_busy)
    if concurrency_gain < 1.5:
        failures.append(
            f"paged engine admitted only {concurrency_gain:.2f}x the "
            f"contiguous concurrency at equal HBM ({paged_busy} vs "
            f"{contiguous_busy}); wanted >= 1.5x")

    # -- queue/SLO/page metrics present in the exposition -------------------
    rendered = get_registry().render()
    for family in ("tpuhive_generate_queue_depth",
                   "tpuhive_generate_slots_busy",
                   "tpuhive_generate_ttft_seconds",
                   "tpuhive_generate_batch_efficiency",
                   "tpuhive_generate_kv_pages_free",
                   "tpuhive_generate_kv_pages_total",
                   "tpuhive_generate_slot_kv_pages",
                   'tpuhive_generate_requests_total{outcome="rejected_queue"}'):
        if family not in rendered:
            failures.append(f"metric missing from exposition: {family}")

    total = len(PROMPT_LENS) * NEW_TOKENS
    print(f"serving-smoke: {len(PROMPT_LENS)} requests x {NEW_TOKENS} tokens "
          f"on {SLOTS} slots | serial {total / serial_s:.1f} tok/s, "
          f"batched {total / batched_s:.1f} tok/s ({speedup:.2f}x) | "
          f"step_execs={engine.step_executable._cache_size()} "
          f"prefill_execs={engine.prefill_executable._cache_size()} | "
          f"over-commit {requested} tokens into {hbm_cells} HBM cells "
          f"(kernel dispatch: {paged.stats()['pagedKernel']}): "
          f"paged {paged_busy} vs contiguous {contiguous_busy} concurrent "
          f"({concurrency_gain:.2f}x) | stats={engine.stats()}")
    for failure in failures:
        print(f"serving-smoke FAILURE: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
