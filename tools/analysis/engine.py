"""thivelint engine: rule registry, shared AST walk, suppressions, baseline.

The static gate grew out of ``tools/lint.py`` (syntax / unused-import /
undefined-name, reference CI parity with mypy+flake8). This package turns it
into a multi-pass analyzer: every pass is a :class:`Rule` registered against
ONE shared parse of each module (the AST plus a parent map is built once per
file, every rule reuses it), with three escape hatches:

* per-line suppression — ``# thive: disable=TH-C`` (comma-separated ids or
  ``*``) on the flagged line;
* a checked-in waiver baseline (``tools/analysis/baseline.json``) for
  findings that are provably safe but beyond the analyzer's reasoning, each
  entry carrying a mandatory human-written ``reason``;
* ``noqa`` on an import line (legacy compatibility for TH-F401).

Output is text (``path:line: RULE message``) or ``--format=json`` for CI
trend artifacts. Exit 0 = no active findings.
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

REPO_ROOT = Path(__file__).resolve().parents[2]

#: the same default walk set as the original tools/lint.py gate
DEFAULT_TARGETS = ("tensorhive_tpu", "tests", "examples", "tools", "bench.py",
                   "__graft_entry__.py")

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

_SUPPRESS_RE = re.compile(r"#\s*thive:\s*disable=([A-Za-z0-9_*,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class Rule:
    """One analysis pass. Subclasses set ``id``/``title``/``rationale`` and
    implement :meth:`check`; ``applies`` scopes the pass to path prefixes
    (posix, repo-relative) so e.g. concurrency discipline is not enforced on
    test fixtures."""

    id: str = ""
    title: str = ""
    rationale: str = ""
    #: repo-relative posix prefixes this rule runs on; empty = everywhere
    scope: Sequence[str] = ()

    def applies(self, relpath: str) -> bool:
        if not self.scope:
            return True
        return any(relpath.startswith(prefix) for prefix in self.scope)

    def check(self, module: "ModuleContext") -> List[Finding]:
        raise NotImplementedError


class ProjectRule(Rule):
    """A pass over the repository as a whole rather than one module —
    cross-artifact contracts (code + docs + UI together, e.g. TH-X) live
    here. Runs once per :func:`run` regardless of the file list (the
    contracts hold whether or not their artifacts are in the walk set,
    and ``--changed-only`` must not let a docs drift slip through).
    Per-line suppressions don't apply (findings may target non-Python
    artifacts); the waiver baseline does."""

    project = True

    def check(self, module: "ModuleContext") -> List[Finding]:
        return []

    def check_project(self, root: Path) -> List[Finding]:
        raise NotImplementedError


_RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    """Register a rule instance (id must be unique)."""
    if rule.id in _RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _RULES[rule.id] = rule
    return rule


def all_rules() -> List[Rule]:
    _load_rules()
    return [_RULES[rule_id] for rule_id in sorted(_RULES)]


_rules_loaded = False


def _load_rules() -> None:
    global _rules_loaded
    if not _rules_loaded:
        from . import rules  # noqa: F401  (import side effect: register())

        _rules_loaded = True


def _parse_suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
    suppressions: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(lines, start=1):
        match = _SUPPRESS_RE.search(line)
        if match:
            ids = {token.strip() for token in match.group(1).split(",")}
            suppressions[lineno] = {token for token in ids if token}
    return suppressions


class ModuleContext:
    """One parsed module shared by every rule: source, AST, parent links,
    and the per-line suppression map."""

    def __init__(self, source: str, relpath: str,
                 path: Optional[Path] = None) -> None:
        self.source = source
        self.relpath = relpath
        self.path = path
        self.lines = source.splitlines()
        self.suppressions = _parse_suppressions(self.lines)
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(source, filename=relpath)
        except SyntaxError as exc:
            self.tree = None
            self.syntax_error = exc
        self._parents: Optional[Dict[int, ast.AST]] = None
        self._dataflow = None

    @property
    def dataflow(self):
        """Shared intra-module dataflow facts (jit wrappers, call-site
        index, module constants — tools/analysis/dataflow.py), built once
        on first use and reused by every flow-aware rule."""
        if self._dataflow is None:
            from .dataflow import Dataflow

            self._dataflow = Dataflow(self)
        return self._dataflow

    @classmethod
    def from_file(cls, path: Path) -> "ModuleContext":
        try:
            relpath = path.resolve().relative_to(REPO_ROOT).as_posix()
        except ValueError:
            relpath = path.as_posix()
        return cls(path.read_text(), relpath, path=path)

    @property
    def parents(self) -> Dict[int, ast.AST]:
        """id(node) -> parent node, built once on first use."""
        if self._parents is None:
            parents: Dict[int, ast.AST] = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        parents[id(child)] = node
            self._parents = parents
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        """Walk from ``node``'s parent up to the module root."""
        parents = self.parents
        current = parents.get(id(node))
        while current is not None:
            yield current
            current = parents.get(id(current))

    def nearest_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for ancestor in self.ancestors(node):
            if isinstance(ancestor, ast.ClassDef):
                return ancestor
        return None

    def suppressed(self, finding: Finding) -> bool:
        ids = self.suppressions.get(finding.line)
        return bool(ids) and (finding.rule in ids or "*" in ids)


# -- baseline ----------------------------------------------------------------

class BaselineError(ValueError):
    pass


class Baseline:
    """Checked-in waivers: each entry matches findings by rule + path +
    message substring and MUST carry a non-empty justification."""

    def __init__(self, waivers: List[Dict[str, str]]) -> None:
        for entry in waivers:
            for key in ("rule", "path", "contains", "reason"):
                if not str(entry.get(key, "")).strip():
                    raise BaselineError(
                        f"baseline entry {entry!r} is missing {key!r} — "
                        "every waiver needs a justified reason")
        self.waivers = waivers
        self.used = [False] * len(waivers)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls([])
        data = json.loads(path.read_text())
        return cls(list(data.get("waivers", [])))

    def waives(self, finding: Finding) -> bool:
        hit = False
        for index, entry in enumerate(self.waivers):
            if (entry["rule"] == finding.rule
                    and entry["path"] == finding.path
                    and entry["contains"] in finding.message):
                self.used[index] = True
                hit = True
        return hit

    def unused(self) -> List[Dict[str, str]]:
        return [entry for entry, used in zip(self.waivers, self.used)
                if not used]


def waiver_for(finding: Finding, reason: str) -> Dict[str, str]:
    """Baseline entry matching exactly this finding (test/CLI helper)."""
    return {"rule": finding.rule, "path": finding.path,
            "contains": finding.message, "reason": reason}


# -- driver ------------------------------------------------------------------

def iter_sources(args: Sequence[str]) -> List[Path]:
    targets = [REPO_ROOT / t for t in (list(args) or DEFAULT_TARGETS)]
    files: List[Path] = []
    for target in targets:
        if target.is_dir():
            files.extend(sorted(target.rglob("*.py")))
        elif target.suffix == ".py":
            files.append(target)
    return files


def changed_files(root: Optional[Path] = None) -> Optional[List[str]]:
    """Repo-relative paths touched vs HEAD (staged + unstaged + untracked),
    for ``--changed-only`` pre-commit runs. None when git is unavailable —
    the caller falls back to the full walk, never to a silent skip."""
    import subprocess

    root = root or REPO_ROOT
    paths: Set[str] = set()
    commands = (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    )
    for argv in commands:
        try:
            proc = subprocess.run(argv, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode != 0:
            return None
        paths.update(line.strip() for line in proc.stdout.splitlines()
                     if line.strip())
    default_dirs = tuple(t for t in DEFAULT_TARGETS
                         if not t.endswith(".py"))
    default_files = tuple(t for t in DEFAULT_TARGETS if t.endswith(".py"))
    return sorted(
        p for p in paths
        if p.endswith(".py") and (root / p).exists()
        and (p in default_files
             or any(p.startswith(d + "/") for d in default_dirs)))


def analyze_source(source: str, relpath: str,
                   rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run rules over an in-memory module; suppressions honored, baseline
    not consulted. The fixture-snippet seam the unit tests drive."""
    module = ModuleContext(source, relpath)
    findings = _check_module(module, rules if rules is not None else all_rules())
    return [f for f in findings if not module.suppressed(f)]


def _check_module(module: ModuleContext, rules: Sequence[Rule]) -> List[Finding]:
    if module.tree is None:
        exc = module.syntax_error
        return [Finding("TH-SYNTAX", module.relpath, exc.lineno or 1,
                        f"syntax error: {exc.msg}")]
    findings: List[Finding] = []
    for rule in rules:
        if rule.applies(module.relpath):
            findings.extend(rule.check(module))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def run(paths: Sequence[str], baseline_path: Optional[Path] = None,
        rule_ids: Optional[Sequence[str]] = None,
        root: Optional[Path] = None) -> Dict[str, object]:
    """Analyze files; returns the full report dict (see keys below).
    Project rules (cross-artifact contracts) run once against ``root``
    regardless of the file list."""
    rules = all_rules()
    if rule_ids:
        wanted = set(rule_ids)
        unknown = wanted - {rule.id for rule in rules}
        if unknown:
            raise SystemExit(f"unknown rule ids: {sorted(unknown)}")
        rules = [rule for rule in rules if rule.id in wanted]
    module_rules = [r for r in rules if not getattr(r, "project", False)]
    project_rules = [r for r in rules if getattr(r, "project", False)]
    baseline = Baseline.load(baseline_path or DEFAULT_BASELINE)
    files = iter_sources(paths)
    active: List[Finding] = []
    suppressed: List[Finding] = []
    waived: List[Finding] = []
    for path in files:
        module = ModuleContext.from_file(path)
        for finding in _check_module(module, module_rules):
            if module.suppressed(finding):
                suppressed.append(finding)
            elif baseline.waives(finding):
                waived.append(finding)
            else:
                active.append(finding)
    for rule in project_rules:
        for finding in sorted(rule.check_project(root or REPO_ROOT),
                              key=lambda f: (f.path, f.line, f.rule)):
            if baseline.waives(finding):
                waived.append(finding)
            else:
                active.append(finding)
    counts: Dict[str, Dict[str, int]] = {}
    for bucket, findings in (("active", active), ("suppressed", suppressed),
                             ("waived", waived)):
        per_rule: Dict[str, int] = {}
        for finding in findings:
            per_rule[finding.rule] = per_rule.get(finding.rule, 0) + 1
        counts[bucket] = dict(sorted(per_rule.items()))
    return {
        "files": len(files),
        "rules": [rule.id for rule in rules],
        "findings": active,
        "suppressed": suppressed,
        "waived": waived,
        "unused_waivers": baseline.unused(),
        "rule_counts": counts,
    }


def to_sarif(report: Dict[str, object]) -> Dict[str, object]:
    """SARIF 2.1.0 payload for CI diff annotation (active findings only —
    suppressed/waived findings are the gate's business, not the diff's)."""
    rules_meta = []
    for rule in all_rules():
        if rule.id in report["rules"]:
            rules_meta.append({
                "id": rule.id,
                "name": rule.title or rule.id,
                "shortDescription": {"text": rule.title or rule.id},
                "fullDescription": {"text": rule.rationale or rule.title},
            })
    results = []
    for finding in report["findings"]:
        results.append({
            "ruleId": finding.rule,
            "level": "error",
            "message": {"text": finding.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": finding.path},
                    "region": {"startLine": max(1, finding.line)},
                },
            }],
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "thivelint",
                "informationUri": "docs/STATIC_ANALYSIS.md",
                "rules": rules_meta,
            }},
            "results": results,
        }],
    }


def refresh_baseline(path: Path, unused: List[Dict[str, str]]) -> int:
    """Drop stale waivers from the baseline file; returns how many."""
    if not unused or not path.exists():
        return 0
    data = json.loads(path.read_text())
    stale = {json.dumps(entry, sort_keys=True) for entry in unused}
    kept = [entry for entry in data.get("waivers", [])
            if json.dumps(entry, sort_keys=True) not in stale]
    dropped = len(data.get("waivers", [])) - len(kept)
    data["waivers"] = kept
    path.write_text(json.dumps(data, indent=2) + "\n")
    return dropped


def main(argv: Optional[Sequence[str]] = None, prog: str = "analysis") -> int:
    parser = argparse.ArgumentParser(
        prog=prog, description="thivelint: the repo's multi-pass static gate")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to analyze (default: repo gate set)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                        help="waiver baseline JSON (default: checked-in)")
    parser.add_argument("--select", default="",
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--changed-only", action="store_true",
                        help="analyze only files changed vs HEAD (pre-commit "
                             "speed; the full walk remains the CI gate). "
                             "Cross-artifact project rules still run.")
    parser.add_argument("--refresh-baseline", action="store_true",
                        help="prune waivers that no longer match any "
                             "finding from the baseline file")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--witness", type=Path, metavar="DUMP",
                        help="compare a runtime lockwitness JSON dump "
                             "(TPUHIVE_LOCK_WITNESS=1 run) against the "
                             "static TH-LOCK graph: observed order edges "
                             "must be a subset of the model and the run "
                             "must be inversion-free")
    options = parser.parse_args(argv)

    if options.list_rules:
        for rule in all_rules():
            scope = ", ".join(rule.scope) if rule.scope else "everywhere"
            kind = " (project)" if getattr(rule, "project", False) else ""
            print(f"{rule.id}: {rule.title} [{scope}]{kind}")
        return 0

    if options.witness is not None:
        # deferred: rules import this module, so the comparator cannot be
        # a top-level import here without a cycle
        from .rules.locks import compare_witness
        ok, lines = compare_witness(options.witness, REPO_ROOT)
        for line in lines:
            print(line, file=sys.stderr)
        return 0 if ok else 1

    paths = list(options.paths)
    if options.changed_only:
        if paths:
            raise SystemExit(f"{prog}: --changed-only and explicit paths "
                             "are mutually exclusive")
        changed = changed_files()
        if changed is None:
            print(f"{prog}: git unavailable; falling back to the full walk",
                  file=sys.stderr)
        else:
            if not changed:
                print(f"{prog}: no changed python files; project-rule "
                      "contracts still checked", file=sys.stderr)
            # an empty change set must NOT fall back to the full walk
            # (iter_sources treats [] as "default targets"); a non-path
            # sentinel yields zero module files while project rules run
            paths = changed or ["__no_changed_files__"]

    selected = [token.strip() for token in options.select.split(",")
                if token.strip()]
    report = run(paths, baseline_path=options.baseline,
                 rule_ids=selected or None)
    findings: List[Finding] = report["findings"]  # type: ignore[assignment]

    if options.format == "json":
        payload = dict(report)
        for key in ("findings", "suppressed", "waived"):
            payload[key] = [f.to_dict() for f in report[key]]
        json.dump(payload, sys.stdout, indent=2)
        sys.stdout.write("\n")
    elif options.format == "sarif":
        json.dump(to_sarif(report), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for finding in findings:
            print(finding.render())

    stale = report["unused_waivers"]
    stale_fails = False
    if stale and options.refresh_baseline:
        dropped = refresh_baseline(options.baseline, stale)
        print(f"{prog}: pruned {dropped} stale waiver(s) from "
              f"{options.baseline}", file=sys.stderr)
    elif stale:
        # a waiver that matches nothing is drift: the code it justified is
        # gone (or moved), so the justification is dead weight that would
        # silently re-waive a future regression. On the FULL default gate
        # (no path/select narrowing — where "matches nothing" is a fact,
        # not an artifact of scoping) that fails the run.
        full_gate = not options.paths and not selected \
            and not options.changed_only
        for entry in stale:
            level = "error" if full_gate else "warning"
            print(f"{prog}: {level}: unused baseline waiver {entry['rule']} "
                  f"{entry['path']!r} ({entry['reason']})", file=sys.stderr)
        if full_gate:
            print(f"{prog}: stale waivers fail the gate — run "
                  f"`python -m tools.analysis --refresh-baseline` to prune "
                  "them (or restore the code they justified)",
                  file=sys.stderr)
            stale_fails = True
    print(f"{prog}: {report['files']} files, {len(findings)} problems "
          f"({len(report['suppressed'])} suppressed, "
          f"{len(report['waived'])} waived)", file=sys.stderr)
    return 1 if findings or stale_fails else 0
