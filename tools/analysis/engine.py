"""thivelint engine: rule registry, shared AST walk, suppressions, baseline.

The static gate grew out of ``tools/lint.py`` (syntax / unused-import /
undefined-name, reference CI parity with mypy+flake8). This package turns it
into a multi-pass analyzer: every pass is a :class:`Rule` registered against
ONE shared parse of each module (the AST plus a parent map is built once per
file, every rule reuses it), with three escape hatches:

* per-line suppression — ``# thive: disable=TH-C`` (comma-separated ids or
  ``*``) on the flagged line;
* a checked-in waiver baseline (``tools/analysis/baseline.json``) for
  findings that are provably safe but beyond the analyzer's reasoning, each
  entry carrying a mandatory human-written ``reason``;
* ``noqa`` on an import line (legacy compatibility for TH-F401).

Output is text (``path:line: RULE message``) or ``--format=json`` for CI
trend artifacts. Exit 0 = no active findings.
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

REPO_ROOT = Path(__file__).resolve().parents[2]

#: the same default walk set as the original tools/lint.py gate
DEFAULT_TARGETS = ("tensorhive_tpu", "tests", "examples", "tools", "bench.py",
                   "__graft_entry__.py")

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

_SUPPRESS_RE = re.compile(r"#\s*thive:\s*disable=([A-Za-z0-9_*,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class Rule:
    """One analysis pass. Subclasses set ``id``/``title``/``rationale`` and
    implement :meth:`check`; ``applies`` scopes the pass to path prefixes
    (posix, repo-relative) so e.g. concurrency discipline is not enforced on
    test fixtures."""

    id: str = ""
    title: str = ""
    rationale: str = ""
    #: repo-relative posix prefixes this rule runs on; empty = everywhere
    scope: Sequence[str] = ()

    def applies(self, relpath: str) -> bool:
        if not self.scope:
            return True
        return any(relpath.startswith(prefix) for prefix in self.scope)

    def check(self, module: "ModuleContext") -> List[Finding]:
        raise NotImplementedError


_RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    """Register a rule instance (id must be unique)."""
    if rule.id in _RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _RULES[rule.id] = rule
    return rule


def all_rules() -> List[Rule]:
    _load_rules()
    return [_RULES[rule_id] for rule_id in sorted(_RULES)]


_rules_loaded = False


def _load_rules() -> None:
    global _rules_loaded
    if not _rules_loaded:
        from . import rules  # noqa: F401  (import side effect: register())

        _rules_loaded = True


def _parse_suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
    suppressions: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(lines, start=1):
        match = _SUPPRESS_RE.search(line)
        if match:
            ids = {token.strip() for token in match.group(1).split(",")}
            suppressions[lineno] = {token for token in ids if token}
    return suppressions


class ModuleContext:
    """One parsed module shared by every rule: source, AST, parent links,
    and the per-line suppression map."""

    def __init__(self, source: str, relpath: str,
                 path: Optional[Path] = None) -> None:
        self.source = source
        self.relpath = relpath
        self.path = path
        self.lines = source.splitlines()
        self.suppressions = _parse_suppressions(self.lines)
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(source, filename=relpath)
        except SyntaxError as exc:
            self.tree = None
            self.syntax_error = exc
        self._parents: Optional[Dict[int, ast.AST]] = None

    @classmethod
    def from_file(cls, path: Path) -> "ModuleContext":
        try:
            relpath = path.resolve().relative_to(REPO_ROOT).as_posix()
        except ValueError:
            relpath = path.as_posix()
        return cls(path.read_text(), relpath, path=path)

    @property
    def parents(self) -> Dict[int, ast.AST]:
        """id(node) -> parent node, built once on first use."""
        if self._parents is None:
            parents: Dict[int, ast.AST] = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        parents[id(child)] = node
            self._parents = parents
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        """Walk from ``node``'s parent up to the module root."""
        parents = self.parents
        current = parents.get(id(node))
        while current is not None:
            yield current
            current = parents.get(id(current))

    def nearest_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for ancestor in self.ancestors(node):
            if isinstance(ancestor, ast.ClassDef):
                return ancestor
        return None

    def suppressed(self, finding: Finding) -> bool:
        ids = self.suppressions.get(finding.line)
        return bool(ids) and (finding.rule in ids or "*" in ids)


# -- baseline ----------------------------------------------------------------

class BaselineError(ValueError):
    pass


class Baseline:
    """Checked-in waivers: each entry matches findings by rule + path +
    message substring and MUST carry a non-empty justification."""

    def __init__(self, waivers: List[Dict[str, str]]) -> None:
        for entry in waivers:
            for key in ("rule", "path", "contains", "reason"):
                if not str(entry.get(key, "")).strip():
                    raise BaselineError(
                        f"baseline entry {entry!r} is missing {key!r} — "
                        "every waiver needs a justified reason")
        self.waivers = waivers
        self.used = [False] * len(waivers)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls([])
        data = json.loads(path.read_text())
        return cls(list(data.get("waivers", [])))

    def waives(self, finding: Finding) -> bool:
        hit = False
        for index, entry in enumerate(self.waivers):
            if (entry["rule"] == finding.rule
                    and entry["path"] == finding.path
                    and entry["contains"] in finding.message):
                self.used[index] = True
                hit = True
        return hit

    def unused(self) -> List[Dict[str, str]]:
        return [entry for entry, used in zip(self.waivers, self.used)
                if not used]


def waiver_for(finding: Finding, reason: str) -> Dict[str, str]:
    """Baseline entry matching exactly this finding (test/CLI helper)."""
    return {"rule": finding.rule, "path": finding.path,
            "contains": finding.message, "reason": reason}


# -- driver ------------------------------------------------------------------

def iter_sources(args: Sequence[str]) -> List[Path]:
    targets = [REPO_ROOT / t for t in (list(args) or DEFAULT_TARGETS)]
    files: List[Path] = []
    for target in targets:
        if target.is_dir():
            files.extend(sorted(target.rglob("*.py")))
        elif target.suffix == ".py":
            files.append(target)
    return files


def analyze_source(source: str, relpath: str,
                   rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run rules over an in-memory module; suppressions honored, baseline
    not consulted. The fixture-snippet seam the unit tests drive."""
    module = ModuleContext(source, relpath)
    findings = _check_module(module, rules if rules is not None else all_rules())
    return [f for f in findings if not module.suppressed(f)]


def _check_module(module: ModuleContext, rules: Sequence[Rule]) -> List[Finding]:
    if module.tree is None:
        exc = module.syntax_error
        return [Finding("TH-SYNTAX", module.relpath, exc.lineno or 1,
                        f"syntax error: {exc.msg}")]
    findings: List[Finding] = []
    for rule in rules:
        if rule.applies(module.relpath):
            findings.extend(rule.check(module))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def run(paths: Sequence[str], baseline_path: Optional[Path] = None,
        rule_ids: Optional[Sequence[str]] = None) -> Dict[str, object]:
    """Analyze files; returns the full report dict (see keys below)."""
    rules = all_rules()
    if rule_ids:
        wanted = set(rule_ids)
        unknown = wanted - {rule.id for rule in rules}
        if unknown:
            raise SystemExit(f"unknown rule ids: {sorted(unknown)}")
        rules = [rule for rule in rules if rule.id in wanted]
    baseline = Baseline.load(baseline_path or DEFAULT_BASELINE)
    files = iter_sources(paths)
    active: List[Finding] = []
    suppressed: List[Finding] = []
    waived: List[Finding] = []
    for path in files:
        module = ModuleContext.from_file(path)
        for finding in _check_module(module, rules):
            if module.suppressed(finding):
                suppressed.append(finding)
            elif baseline.waives(finding):
                waived.append(finding)
            else:
                active.append(finding)
    return {
        "files": len(files),
        "rules": [rule.id for rule in rules],
        "findings": active,
        "suppressed": suppressed,
        "waived": waived,
        "unused_waivers": baseline.unused(),
    }


def main(argv: Optional[Sequence[str]] = None, prog: str = "analysis") -> int:
    parser = argparse.ArgumentParser(
        prog=prog, description="thivelint: the repo's multi-pass static gate")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to analyze (default: repo gate set)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                        help="waiver baseline JSON (default: checked-in)")
    parser.add_argument("--select", default="",
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    options = parser.parse_args(argv)

    if options.list_rules:
        for rule in all_rules():
            scope = ", ".join(rule.scope) if rule.scope else "everywhere"
            print(f"{rule.id}: {rule.title} [{scope}]")
        return 0

    selected = [token.strip() for token in options.select.split(",")
                if token.strip()]
    report = run(options.paths, baseline_path=options.baseline,
                 rule_ids=selected or None)
    findings: List[Finding] = report["findings"]  # type: ignore[assignment]

    if options.format == "json":
        payload = dict(report)
        for key in ("findings", "suppressed", "waived"):
            payload[key] = [f.to_dict() for f in report[key]]
        json.dump(payload, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for finding in findings:
            print(finding.render())
    for entry in report["unused_waivers"]:
        print(f"{prog}: warning: unused baseline waiver {entry['rule']} "
              f"{entry['path']!r} ({entry['reason']})", file=sys.stderr)
    print(f"{prog}: {report['files']} files, {len(findings)} problems "
          f"({len(report['suppressed'])} suppressed, "
          f"{len(report['waived'])} waived)", file=sys.stderr)
    return 1 if findings else 0
