"""TH-C: lock discipline in classes that own a threading lock.

The control plane is a set of daemon threads (services, transport pool, API
request threads) sharing mutable state under ad-hoc locks. Two defect shapes
this pass catches:

* an instance attribute written both inside ``with self._lock:`` and outside
  it — the unguarded write races every guarded reader/writer;
* a blocking call (``time.sleep``, ``subprocess.*``) executed while holding
  a lock — every other thread touching that lock stalls for the duration.

Scope: a class "owns" a lock when any method assigns ``self.<attr>`` a
``threading.Lock/RLock/Condition`` (directly or via ``lock or Lock()``).
``__init__``/``__new__`` writes are construction (happens-before publication)
and never flagged. Locks acquired through other objects or custom guards
(e.g. ``RWLock.write()``) are beyond this pass — waive with a justified
baseline entry where a human has proven the path safe.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..dataflow import (class_lock_attrs, is_lock_value, is_locked_name,
                        self_attr)
from ..engine import Finding, ModuleContext, Rule, register

#: calls that block the holder for an unbounded / scheduled duration
BLOCKING_CALLS = {
    ("time", "sleep"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("subprocess", "Popen"),
}

#: method calls that mutate a container in place
MUTATOR_METHODS = {"append", "add", "update", "extend", "insert", "remove",
                   "pop", "popitem", "clear", "discard", "setdefault",
                   "appendleft"}

CONSTRUCTORS = {"__init__", "__new__"}


# the lock vocabulary (factories, ``self.X`` spelling, the ``_locked``
# convention) lives in dataflow so TH-C / TH-REF / TH-LOCK share one
# definition; these aliases keep the historical import surface stable
_self_attr = self_attr
_is_lock_value = is_lock_value


def _dotted(func: ast.AST) -> Optional[Tuple[str, str]]:
    """``mod.attr(...)`` -> ("mod", "attr") for plain Name receivers."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (func.value.id, func.attr)
    return None


class LockDisciplineRule(Rule):
    id = "TH-C"
    title = "inconsistent lock discipline / blocking call under lock"
    rationale = ("State shared across daemon threads must be mutated under "
                 "its lock every time, and locks must not be held across "
                 "blocking calls.")
    scope = ("tensorhive_tpu/", "tools/", "tests/")

    def check(self, module: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        return findings

    # ------------------------------------------------------------------
    def _class_nodes(self, module: ModuleContext, cls: ast.ClassDef):
        """Nodes whose nearest enclosing ClassDef is ``cls`` (nested classes
        are analyzed on their own)."""
        for node in ast.walk(cls):
            if node is cls:
                continue
            if module.nearest_class(node) is cls:
                yield node

    def _lock_attrs(self, module: ModuleContext, cls: ast.ClassDef) -> Set[str]:
        return set(class_lock_attrs(module, cls))

    def _enclosing_method(self, module: ModuleContext,
                          node: ast.AST) -> Optional[str]:
        for ancestor in module.ancestors(node):
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return ancestor.name
            if isinstance(ancestor, ast.ClassDef):
                return None
        return None

    def _held_lock(self, module: ModuleContext, node: ast.AST,
                   lock_attrs: Set[str]) -> Optional[str]:
        """The class lock held at ``node`` (lexically inside a
        ``with self.<lock>:``), or None."""
        for ancestor in module.ancestors(node):
            if isinstance(ancestor, (ast.With, ast.AsyncWith)):
                for item in ancestor.items:
                    attr = _self_attr(item.context_expr)
                    if attr in lock_attrs:
                        return attr
            if isinstance(ancestor, ast.ClassDef):
                break
        return None

    def _check_class(self, module: ModuleContext,
                     cls: ast.ClassDef) -> List[Finding]:
        lock_attrs = self._lock_attrs(module, cls)
        if not lock_attrs:
            return []
        findings: List[Finding] = []
        # attr -> (guarded linenos, unguarded (lineno, method) sites)
        guarded: Dict[str, List[int]] = {}
        unguarded: Dict[str, List[Tuple[int, str]]] = {}

        def record(attr: Optional[str], node: ast.AST) -> None:
            if attr is None or attr in lock_attrs:
                return
            method = self._enclosing_method(module, node)
            if method is None or method in CONSTRUCTORS:
                return
            # the _locked suffix is the caller-holds-the-lock contract
            # (TH-REF enforces the call sites); writes inside such a
            # method are guarded by convention, not by a lexical `with`
            if (self._held_lock(module, node, lock_attrs)
                    or is_locked_name(method)):
                guarded.setdefault(attr, []).append(node.lineno)
            else:
                unguarded.setdefault(attr, []).append((node.lineno, method))

        for node in self._class_nodes(module, cls):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    record(_self_attr(target), node)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                record(_self_attr(node.target), node)
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    record(_self_attr(target), node)
            elif isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in MUTATOR_METHODS):
                    record(_self_attr(func.value), node)
                # blocking call while holding the class lock
                dotted = _dotted(func)
                if dotted in BLOCKING_CALLS:
                    held = self._held_lock(module, node, lock_attrs)
                    if held is not None:
                        findings.append(Finding(
                            self.id, module.relpath, node.lineno,
                            f"blocking call {dotted[0]}.{dotted[1]}() while "
                            f"holding self.{held} (class {cls.name}) stalls "
                            "every thread contending on the lock"))

        for attr, sites in unguarded.items():
            if attr not in guarded:
                continue        # never guarded: not this pass's contract
            lock_name = sorted(lock_attrs)[0]
            for lineno, method in sites:
                findings.append(Finding(
                    self.id, module.relpath, lineno,
                    f"self.{attr} is mutated under self.{lock_name} "
                    f"elsewhere (e.g. line {min(guarded[attr])}) but written "
                    f"without it here (method {method}, class {cls.name})"))
        return findings


register(LockDisciplineRule())
