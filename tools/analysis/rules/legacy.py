"""The original tools/lint.py checks as registered passes.

TH-F401 (unused imports) and TH-F821 (undefined names, module-flat subset)
keep the exact conservative semantics the repo gate has enforced since PR 0;
TH-SYNTAX is emitted by the engine when a file fails to parse. ``noqa`` on an
import line is honored for back-compat with existing annotations, alongside
the ``# thive: disable=`` syntax.
"""
from __future__ import annotations

import ast
import builtins
from typing import List

from ..engine import Finding, ModuleContext, Rule, register

#: names every module may reference without defining (dunders + pytest)
IMPLICIT = {"__file__", "__name__", "__doc__", "__package__", "__spec__",
            "__builtins__", "__debug__", "__class__"}

BUILTIN_NAMES = set(dir(builtins)) | IMPLICIT


class NameCollector(ast.NodeVisitor):
    """All identifiers read or written anywhere in the module; first read
    lineno retained so findings are line-addressable (suppressible)."""

    def __init__(self) -> None:
        self.read = {}          # name -> first read lineno
        self.bound = set()

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.read.setdefault(node.id, node.lineno)
        else:
            self.bound.add(node.id)
        self.generic_visit(node)

    def _bind_args(self, args: ast.arguments) -> None:
        for arg in ([*args.posonlyargs, *args.args, *args.kwonlyargs]
                    + ([args.vararg] if args.vararg else [])
                    + ([args.kwarg] if args.kwarg else [])):
            self.bound.add(arg.arg)

    def visit_FunctionDef(self, node) -> None:
        self.bound.add(node.name)
        self._bind_args(node.args)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self.bound.update(node.names)

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self.bound.update(node.names)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._bind_args(node.args)
        self.generic_visit(node)


def imported_names(tree: ast.AST):
    """(bound name, lineno, display) for every import binding."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                out.append((bound, node.lineno, alias.name))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                out.append((bound, node.lineno, alias.name))
    return out


def string_literals(tree: ast.AST):
    """String constants — names referenced in __all__, TYPE_CHECKING hints,
    or docstring doctests count as uses (conservative)."""
    found = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for token in node.value.replace(".", " ").replace(",", " ").split():
                if token.isidentifier():
                    found.add(token)
    return found


class UnusedImportRule(Rule):
    id = "TH-F401"
    title = "unused import"
    rationale = ("An import bound but never read is dead weight and often a "
                 "refactor leftover; __init__.py re-exports are exempt.")

    def check(self, module: ModuleContext) -> List[Finding]:
        tree = module.tree
        if module.relpath.endswith("__init__.py"):
            return []       # __init__ imports are the package's public API
        collector = NameCollector()
        collector.visit(tree)
        strings = string_literals(tree)
        findings = []
        for bound, lineno, display in imported_names(tree):
            line = (module.lines[lineno - 1]
                    if lineno - 1 < len(module.lines) else "")
            if "noqa" in line:
                continue
            if bound not in collector.read and bound not in strings:
                findings.append(Finding(
                    self.id, module.relpath, lineno,
                    f"unused import: {display}"))
        return findings


class UndefinedNameRule(Rule):
    id = "TH-F821"
    title = "undefined name (module-flat subset)"
    rationale = ("A name read anywhere but bound nowhere in the module, not "
                 "imported, and not a builtin is a NameError waiting for its "
                 "code path. Module-flat = zero scope-model false positives.")

    def check(self, module: ModuleContext) -> List[Finding]:
        tree = module.tree
        has_star = any(
            isinstance(node, ast.ImportFrom)
            and any(a.name == "*" for a in node.names)
            for node in ast.walk(tree))
        if has_star:
            return []
        collector = NameCollector()
        collector.visit(tree)
        imported = {bound for bound, _, _ in imported_names(tree)}
        known = collector.bound | imported | BUILTIN_NAMES
        return [
            Finding(self.id, module.relpath, lineno,
                    f"undefined name: {name}")
            for name, lineno in sorted(collector.read.items())
            if name not in known
        ]


register(UnusedImportRule())
register(UndefinedNameRule())
