"""TH-J: JAX host-sync and trace-purity defects in the compute stack.

The ROADMAP north star is "as fast as the hardware allows"; the quietest way
to lose that is a device→host synchronization on the hot path. Two shapes:

* **Trace purity**: ``float()``, ``int()``, ``.item()``, ``np.asarray``/
  ``np.array`` or ``jax.device_get`` applied to a traced value inside a
  ``@jax.jit``/``@jax.pmap``-decorated function either fails at trace time
  (ConcretizationTypeError) or — worse — silently bakes a constant into the
  compiled program.
* **Per-iteration eval-loop syncs** (``tensorhive_tpu/{models,ops,parallel}``
  only): host conversions (``float(...)``, ``.item()``, ``np.asarray``,
  ``jax.device_get``, ``.block_until_ready()``) inside a ``for``/``while``
  loop body force one blocking device round-trip per batch, serializing the
  async dispatch pipeline. Accumulate on device and convert ONCE after the
  loop (measured pattern: models/decode.py evaluate / models/encoder.py
  mlm_evaluate).

Lexical, like the rest of the gate: functions jitted at call sites
(``jax.jit(f)``) are not chased.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..engine import Finding, ModuleContext, Rule, register

JIT_NAMES = {"jit", "pmap"}
HOST_CONVERSIONS = {"float", "int"}
HOST_METHODS = {"item", "block_until_ready"}
#: loops in these subtrees are assumed to iterate over device computations
LOOP_SCOPES = ("tensorhive_tpu/models/", "tensorhive_tpu/ops/",
               "tensorhive_tpu/parallel/")


def _decorator_is_jit(decorator: ast.AST) -> bool:
    """@jit / @jax.jit / @jit(...) / @functools.partial(jax.jit, ...)."""
    if isinstance(decorator, ast.Call):
        if any(_decorator_is_jit(arg) for arg in decorator.args):
            return True     # functools.partial(jax.jit, ...)
        decorator = decorator.func
    if isinstance(decorator, ast.Name):
        return decorator.id in JIT_NAMES
    if isinstance(decorator, ast.Attribute):
        return decorator.attr in JIT_NAMES
    return False


def _host_sync_call(node: ast.Call) -> Optional[str]:
    """Name of the host-forcing operation, or None."""
    func = node.func
    if isinstance(func, ast.Name) and func.id in HOST_CONVERSIONS:
        # float(0.5) is a constant, float(x) forces the device value
        if node.args and not isinstance(node.args[0], ast.Constant):
            return f"{func.id}()"
        return None
    if isinstance(func, ast.Attribute):
        if func.attr in HOST_METHODS:
            return f".{func.attr}()"
        receiver = func.value.id if isinstance(func.value, ast.Name) else None
        if receiver in {"np", "numpy"} and func.attr in {"asarray", "array"}:
            return f"{receiver}.{func.attr}()"
        if receiver == "jax" and func.attr == "device_get":
            return "jax.device_get()"
    return None


class JaxHostSyncRule(Rule):
    id = "TH-J"
    title = "host sync / impurity on the JAX hot path"
    rationale = ("Device->host conversions inside jitted functions break "
                 "tracing; inside eval loops they serialize async dispatch "
                 "to one blocking round-trip per batch.")
    #: the jitted-purity half runs everywhere jax code lives (tools smoke
    #: scripts and tests define real jitted fns too); the loop half stays
    #: scoped to LOOP_SCOPES where loop bodies plausibly hold device values
    scope = ("tensorhive_tpu/", "tools/", "tests/", "bench.py")

    def check(self, module: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_jitted(module))
        if module.relpath.startswith(LOOP_SCOPES):
            findings.extend(self._check_loops(module))
        return findings

    # -- purity inside @jit/@pmap ------------------------------------------
    def _check_jitted(self, module: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(_decorator_is_jit(d) for d in node.decorator_list):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    op = _host_sync_call(sub)
                    if op is not None:
                        findings.append(Finding(
                            self.id, module.relpath, sub.lineno,
                            f"{op} on a traced value inside jitted "
                            f"{node.name}() either fails to trace or bakes "
                            "in a constant"))
        return findings

    # -- per-iteration syncs in eval/train loops ---------------------------
    def _check_loops(self, module: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for sub in ast.walk(loop):
                if sub is loop or not isinstance(sub, ast.Call):
                    continue
                # only direct loop-body calls: a nested loop's findings are
                # reported once, for the innermost loop containing them
                if self._innermost_loop(module, sub) is not loop:
                    continue
                op = _host_sync_call(sub)
                if op is not None:
                    findings.append(Finding(
                        self.id, module.relpath, sub.lineno,
                        f"{op} inside a loop forces one device->host sync "
                        "per iteration; accumulate on device and convert "
                        "once after the loop"))
        return findings

    @staticmethod
    def _innermost_loop(module: ModuleContext, node: ast.AST):
        for ancestor in module.ancestors(node):
            if isinstance(ancestor, (ast.For, ast.While)):
                return ancestor
        return None


register(JaxHostSyncRule())
