"""TH-REF: refcounted-resource pairing and the ``_locked`` convention.

The paged serving engine's correctness hangs on exact pairing: every
``PagePool.assign``/``assign_shared`` grant is undone by ``release``, every
prefix-tree ``cache_ref`` retention by ``cache_unref`` — an unpaired
acquire leaks pages until admission starves (the "free+live == pool_size"
invariant the churn property tests pin), and the bug class costs exactly
what a memory leak costs: nothing fails, capacity just evaporates. Checks,
on the lexical receiver spelling (``self._pool``, ``pool`` — not chased
through aliases):

* **unpaired acquire** — a class (or module top level) that calls an
  acquire method on some receiver but never the paired release on the same
  receiver. Classes that *define* the paired release are the resource
  itself, not a holder, and are exempt (``PagePool.assign`` calling
  ``self.assign_shared`` is implementation, not holding).
* **early return between acquire and release** — inside one function that
  both acquires and releases a receiver, a ``return`` between the two
  leaks the grant on that path; a release in a ``finally`` that encloses
  the return is recognized as covering it.
* **swallowed-exception leak** — an acquire inside a ``try`` whose broad
  handler neither releases, re-raises, nor returns the resource: the
  failure path keeps the grant with nobody holding it.

The ``_locked`` suffix is this codebase's caller-holds-the-lock contract
(serving/engine.py): a method named ``*_locked`` asserts its caller
already holds the instance lock. Two violations:

* a ``*_locked`` method that ACQUIRES the class lock itself — instant
  deadlock on a plain ``threading.Lock`` the moment the contract is
  honored by the caller;
* a call to ``self.*_locked(...)`` from outside any ``with self.<lock>:``
  block and outside another ``*_locked`` method — the contract broken at
  the call site, i.e. unguarded mutation of guarded state.

(TH-C consumes the same convention from the other side: writes inside a
``*_locked`` method count as guarded.)
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..dataflow import (class_lock_attrs, dotted_source, is_locked_name,
                        self_attr)
from ..engine import Finding, ModuleContext, Rule, register

#: acquire method name -> the release method that must pair with it
PAIRS = {
    "assign": "release",
    "assign_shared": "release",
    "cache_ref": "cache_unref",
}
RELEASES = set(PAIRS.values())
BROAD_TYPES = {"Exception", "BaseException"}


def _method_call(node: ast.Call) -> Optional[Tuple[str, str]]:
    """(receiver-spelling, method) for ``recv.method(...)`` calls."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    receiver = dotted_source(func.value)
    if receiver is None:
        return None
    return receiver, func.attr


class RefcountPairingRule(Rule):
    id = "TH-REF"
    title = "unpaired resource acquire / leak path / _locked convention break"
    rationale = ("Page grants and cache retentions must pair exactly — an "
                 "unpaired acquire or a leaking early-return/except path "
                 "bleeds pool capacity with no failure; _locked methods "
                 "must be called with the lock actually held.")
    scope = ("tensorhive_tpu/", "tools/")

    def check(self, module: ModuleContext) -> List[Finding]:
        if module.tree is None:
            return []
        findings: List[Finding] = []
        findings.extend(self._check_pairing(module))
        findings.extend(self._check_leak_paths(module))
        findings.extend(self._check_locked_convention(module))
        return findings

    # -- scope grouping -----------------------------------------------------
    def _owner_of(self, module: ModuleContext,
                  node: ast.AST) -> Optional[ast.ClassDef]:
        return module.nearest_class(node)

    def _defined_methods(self, cls: Optional[ast.ClassDef]) -> Set[str]:
        if cls is None:
            return set()
        return {stmt.name for stmt in cls.body
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))}

    # -- unpaired acquires --------------------------------------------------
    def _check_pairing(self, module: ModuleContext) -> List[Finding]:
        # owner (class node id or None) -> {method: [(receiver, call)]}
        acquires: Dict[Optional[int], List[Tuple[str, str, ast.Call]]] = {}
        releases: Dict[Optional[int], Set[Tuple[str, str]]] = {}
        owners: Dict[Optional[int], Optional[ast.ClassDef]] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            spelled = _method_call(node)
            if spelled is None:
                continue
            receiver, method = spelled
            cls = self._owner_of(module, node)
            key = id(cls) if cls is not None else None
            owners[key] = cls
            if method in PAIRS:
                acquires.setdefault(key, []).append((receiver, method, node))
            if method in RELEASES:
                releases.setdefault(key, set()).add((receiver, method))
        findings: List[Finding] = []
        for key, sites in acquires.items():
            cls = owners.get(key)
            defined = self._defined_methods(cls)
            for receiver, method, call in sites:
                release = PAIRS[method]
                if release in defined:
                    continue    # the resource's own implementation
                if (receiver, release) in releases.get(key, set()):
                    continue
                where = f"class {cls.name}" if cls is not None else "module"
                findings.append(Finding(
                    self.id, module.relpath, call.lineno,
                    f"{receiver}.{method}() acquires a refcounted resource "
                    f"but {where} never calls {receiver}.{release}() — "
                    "the grant can never be returned (pool capacity "
                    "leaks)"))
        return findings

    # -- early returns / swallowed exceptions between acquire and release --
    def _check_leak_paths(self, module: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            calls = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    spelled = _method_call(node)
                    if spelled is not None:
                        calls.append((spelled[0], spelled[1], node))
            for receiver, method, acquire in calls:
                if method not in PAIRS:
                    continue
                release = PAIRS[method]
                release_sites = [c for r, m, c in calls
                                 if r == receiver and m == release]
                if release_sites:
                    findings.extend(self._early_returns(
                        module, fn, receiver, method, acquire,
                        release_sites))
                findings.extend(self._swallowed_paths(
                    module, fn, receiver, method, release, acquire, calls))
        return findings

    def _early_returns(self, module: ModuleContext, fn: ast.AST,
                       receiver: str, method: str, acquire: ast.Call,
                       release_sites: List[ast.Call]) -> List[Finding]:
        last_release = max(c.lineno for c in release_sites)
        in_finally = any(self._in_enclosing_finally(module, c, fn)
                         for c in release_sites)
        findings: List[Finding] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return):
                continue
            if not acquire.lineno < node.lineno < last_release:
                continue
            if module.dataflow.enclosing_function(node) is not fn:
                continue
            if not module.dataflow.same_branch(acquire, node):
                continue
            if in_finally:
                continue        # finally runs on every return path
            findings.append(Finding(
                self.id, module.relpath, node.lineno,
                f"early return between {receiver}.{method}() (line "
                f"{acquire.lineno}) and {receiver}.{PAIRS[method]}() "
                f"(line {last_release}) leaks the grant on this path — "
                "release in a finally:, or before returning"))
        return findings

    def _swallowed_paths(self, module: ModuleContext, fn: ast.AST,
                         receiver: str, method: str, release: str,
                         acquire: ast.Call, calls) -> List[Finding]:
        findings: List[Finding] = []
        for ancestor in module.ancestors(acquire):
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if not isinstance(ancestor, ast.Try):
                continue
            if ancestor.finalbody and any(
                    c.lineno for r, m, c in calls
                    if r == receiver and m == release
                    and self._inside(module, c, ancestor.finalbody)):
                continue        # finally releases: every path covered
            # only flag when the acquire is in the TRY BODY (not a handler)
            if self._branch_of_try(module, acquire, ancestor) != "body":
                continue
            for handler in ancestor.handlers:
                if not self._is_broad(handler):
                    continue
                handled = [(r, m) for r, m, c in calls
                           if self._inside(module, c, handler.body)]
                if (receiver, release) in handled:
                    continue
                if any(isinstance(n, ast.Raise)
                       for stmt in handler.body for n in ast.walk(stmt)):
                    continue
                findings.append(Finding(
                    self.id, module.relpath, handler.lineno,
                    f"broad except swallows failures after "
                    f"{receiver}.{method}() (line {acquire.lineno}) "
                    f"without calling {receiver}.{release}() — the "
                    "exception path leaks the grant"))
        return findings

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        if isinstance(handler.type, ast.Name):
            return handler.type.id in BROAD_TYPES
        if isinstance(handler.type, ast.Tuple):
            return any(isinstance(e, ast.Name) and e.id in BROAD_TYPES
                       for e in handler.type.elts)
        return False

    @staticmethod
    def _inside(module: ModuleContext, node: ast.AST, stmts) -> bool:
        chain = {id(node)} | {id(a) for a in module.ancestors(node)}
        return any(id(stmt) in chain for stmt in stmts)

    def _branch_of_try(self, module: ModuleContext, node: ast.AST,
                       try_node: ast.Try) -> Optional[str]:
        if self._inside(module, node, try_node.body):
            return "body"
        if self._inside(module, node, try_node.orelse):
            return "orelse"
        if self._inside(module, node, try_node.finalbody):
            return "finally"
        return "handler"

    def _in_enclosing_finally(self, module: ModuleContext, node: ast.AST,
                              fn: ast.AST) -> bool:
        for ancestor in module.ancestors(node):
            if ancestor is fn:
                break
            if isinstance(ancestor, ast.Try) and \
                    self._inside(module, node, ancestor.finalbody):
                return True
        return False

    # -- the _locked convention --------------------------------------------
    def _check_locked_convention(self, module: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_attrs = self._lock_attrs(module, cls)
            if not lock_attrs:
                continue
            findings.extend(self._check_class_locked(module, cls,
                                                     lock_attrs))
        return findings

    def _lock_attrs(self, module: ModuleContext,
                    cls: ast.ClassDef) -> Set[str]:
        return set(class_lock_attrs(module, cls))

    def _check_class_locked(self, module: ModuleContext, cls: ast.ClassDef,
                            lock_attrs: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(cls):
            if module.nearest_class(node) is not cls:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and is_locked_name(node.name):
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.With, ast.AsyncWith)):
                        for item in sub.items:
                            attr = self_attr(item.context_expr)
                            if attr in lock_attrs:
                                findings.append(Finding(
                                    self.id, module.relpath, sub.lineno,
                                    f"{node.name}() acquires self.{attr} "
                                    "— its _locked suffix promises the "
                                    "caller already holds it (deadlock "
                                    "on a non-reentrant Lock)"))
            if isinstance(node, ast.Call):
                func = node.func
                if not (isinstance(func, ast.Attribute)
                        and is_locked_name(func.attr)
                        and isinstance(func.value, ast.Name)
                        and func.value.id == "self"):
                    continue
                if self._lock_held(module, node, lock_attrs):
                    continue
                findings.append(Finding(
                    self.id, module.relpath, node.lineno,
                    f"self.{func.attr}() called without holding "
                    f"{'/'.join('self.' + a for a in sorted(lock_attrs))} "
                    "— the _locked suffix is the caller-holds-the-lock "
                    "contract (wrap the call in `with self._lock:` or "
                    "call from another _locked method)"))
        return findings

    @staticmethod
    def _lock_held(module: ModuleContext, node: ast.AST,
                   lock_attrs: Set[str]) -> bool:
        for ancestor in module.ancestors(node):
            if isinstance(ancestor, (ast.With, ast.AsyncWith)):
                for item in ancestor.items:
                    if self_attr(item.context_expr) in lock_attrs:
                        return True
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return is_locked_name(ancestor.name)
            if isinstance(ancestor, ast.ClassDef):
                break
        return False


register(RefcountPairingRule())
