"""TH-B: blocking calls without a deadline in latency-sensitive paths.

Two kinds of function are on the serving hot path: API handler functions
(decorated with ``@route(...)`` — one slow handler stalls a worker thread
and every request queued behind it) and ``Service.do_run`` tick bodies (one
hung tick starves the poll cadence for the whole cluster — monitoring,
protection and scheduling all ride a 2-30 s loop).

Inside those functions this pass flags, lexically:

* ``time.sleep(...)`` — always (handlers must not sleep; services sleep via
  the interruptible ``StoppableThread.wait``);
* ``subprocess.run/call/check_call/check_output/Popen(...)`` without a
  ``timeout=`` keyword;
* transport fan-out calls (``.run_on_all(...)``, ``.check_output(...)``)
  without a ``timeout=`` keyword — an unreachable host must cost a bounded
  wait, never a hung tick.

The analysis is lexical (calls made by helpers the hot path invokes are not
chased); it catches the shape that actually regresses: the blocking call
written directly into the handler/tick body.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..engine import Finding, ModuleContext, Rule, register

SUBPROCESS_CALLS = {"run", "call", "check_call", "check_output", "Popen"}
TRANSPORT_CALLS = {"run_on_all", "check_output"}


def _is_hot_path(node: ast.AST) -> Optional[str]:
    """'handler' for @route-decorated functions, 'do_run tick' for service
    tick bodies, else None."""
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    if node.name == "do_run":
        return "do_run tick"
    for decorator in node.decorator_list:
        target = decorator.func if isinstance(decorator, ast.Call) else decorator
        name = target.id if isinstance(target, ast.Name) else (
            target.attr if isinstance(target, ast.Attribute) else None)
        if name == "route":
            return "API handler"
    return None


def _has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


class BlockingCallRule(Rule):
    id = "TH-B"
    title = "blocking call without timeout in API handler / service tick"
    rationale = ("A handler or tick that blocks without a deadline turns one "
                 "slow host into a stalled control plane.")
    scope = ("tensorhive_tpu/", "tools/", "tests/")

    def check(self, module: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            kind = _is_hot_path(node)
            if kind is None:
                continue
            findings.extend(self._check_body(module, node, kind))
        return findings

    def _check_body(self, module: ModuleContext, fn: ast.AST,
                    kind: str) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            receiver = (func.value.id
                        if isinstance(func.value, ast.Name) else None)
            if receiver == "time" and func.attr == "sleep":
                findings.append(Finding(
                    self.id, module.relpath, node.lineno,
                    f"time.sleep in {kind} blocks the thread; use an "
                    "interruptible wait outside the hot path"))
            elif (receiver == "subprocess" and func.attr in SUBPROCESS_CALLS
                    and not _has_timeout(node)):
                findings.append(Finding(
                    self.id, module.relpath, node.lineno,
                    f"subprocess.{func.attr} without timeout= in {kind} can "
                    "hang the thread on a wedged child"))
            elif func.attr in TRANSPORT_CALLS and not _has_timeout(node):
                findings.append(Finding(
                    self.id, module.relpath, node.lineno,
                    f".{func.attr}(...) without timeout= in {kind}: an "
                    "unreachable host must cost a bounded wait"))
        return findings


register(BlockingCallRule())
