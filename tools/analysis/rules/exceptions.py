"""TH-E: exception hygiene.

Silently swallowed exceptions are how this codebase's failures historically
died invisible (the reference's service threads had no guard at all — a
monitor exception stopped all monitoring with no trace). The contract this
pass enforces: a broad handler (``except:``, ``except Exception:``,
``except BaseException:``) must do at least one of

* re-raise (``raise`` anywhere in the body),
* log (any ``.exception/.error/.warning/.info/.debug/.critical/.log`` call),
* record a metric (``.inc/.dec/.observe/.set/.labels`` call — the
  docs/OBSERVABILITY.md "count swallowed exceptions" guidance), or
* actually consume the bound exception object (``except Exception as exc``
  with ``exc`` read in the body — the value flows somewhere, it is not
  silent).

Narrow handlers (``except OSError:``) are trusted: naming the type is the
author stating which failure is expected. The pass also flags mutable
default arguments (``def f(x=[])``) — shared-state-across-calls bugs that
read like per-call state.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..engine import Finding, ModuleContext, Rule, register

BROAD = {"Exception", "BaseException"}
LOG_METHODS = {"exception", "error", "warning", "warn", "info", "debug",
               "critical", "log"}
METRIC_METHODS = {"inc", "dec", "observe", "set", "labels"}


def _broad_name(type_node: Optional[ast.AST]) -> Optional[str]:
    """'Exception'/'BaseException'/'bare' when the handler is broad."""
    if type_node is None:
        return "bare"
    if isinstance(type_node, ast.Name) and type_node.id in BROAD:
        return type_node.id
    if isinstance(type_node, ast.Tuple):
        for element in type_node.elts:
            if isinstance(element, ast.Name) and element.id in BROAD:
                return element.id
    return None


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in LOG_METHODS | METRIC_METHODS:
                return False
        if (handler.name is not None and isinstance(node, ast.Name)
                and node.id == handler.name
                and isinstance(node.ctx, ast.Load)):
            return False
    return True


def _mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    return (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in {"list", "dict", "set"} and not node.args
            and not node.keywords)


class ExceptionHygieneRule(Rule):
    id = "TH-E"
    title = "silent broad exception handler / mutable default argument"
    rationale = ("except Exception: pass makes production failures "
                 "undiagnosable; broad handlers must log, re-raise, count a "
                 "metric, or consume the exception value.")
    scope = ("tensorhive_tpu/", "tools/", "tests/", "bench.py")

    def check(self, module: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ExceptHandler):
                broad = _broad_name(node.type)
                if broad is not None and _handler_is_silent(node):
                    what = ("bare except:" if broad == "bare"
                            else f"except {broad}:")
                    findings.append(Finding(
                        self.id, module.relpath, node.lineno,
                        f"{what} swallows the exception silently — log it, "
                        "re-raise, count a metric, or narrow the type"))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defaults = (list(node.args.defaults)
                            + [d for d in node.args.kw_defaults
                               if d is not None])
                for default in defaults:
                    if _mutable_default(default):
                        findings.append(Finding(
                            self.id, module.relpath, default.lineno,
                            f"mutable default argument in {node.name}() is "
                            "shared across calls; default to None and "
                            "construct inside"))
        return findings


register(ExceptionHygieneRule())
