"""TH-DON: donation discipline around jit wrappers (flow-aware).

Donation is XLA input-output aliasing: a ``donate_argnames`` buffer is
reused for an output of the SAME shape — which only happens when the
donated value actually flows into the return. Two failure shapes, both
learned the hard way in PR 3 (CHANGES.md: "returning tokens alone left the
cache donation unusable"):

* **donated-but-not-returned** — the jit target has a return path through
  which no value derived from the donated parameter flows. XLA cannot
  alias, the donation buys nothing (and jax warns at runtime, where nobody
  is looking); worse, the caller's buffer is still dead afterward. Taint
  is propagated through assignments (tuple unpacking and closure
  ``nonlocal`` rebinding included), so ``cache_k, cache_v = cache.k,
  cache.v`` keeps the cache tainted through the body.
* **use-after-donate** — a call site passes a buffer in donated position
  and then reads the same name again on a reachable path without rebinding
  it first. The donated buffer is DEAD after dispatch; reading it is a
  runtime error on real backends. The canonical safe shape — rebinding the
  result over the operand, ``self._cache = step(..., self._cache, ...)``
  — is recognized and exempt, as are reads in mutually-exclusive branches
  (``dataflow.same_branch``).

Resolution is via the shared dataflow layer: every wrapper spelling the
repo uses (partial-jit assignments, direct ``jax.jit`` calls, decorators)
is recognized; wrappers whose target function lives elsewhere are not
chased (lexical, like the whole gate).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..dataflow import Dataflow, JitWrapper, call_argument, dotted_source
from ..engine import Finding, ModuleContext, Rule, register


def _assign_targets(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    if isinstance(node, ast.NamedExpr):
        return [node.target]
    return []


def _flat_names(node: ast.AST) -> List[str]:
    names: List[str] = []
    if isinstance(node, ast.Name):
        names.append(node.id)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for element in node.elts:
            names.extend(_flat_names(element))
    elif isinstance(node, ast.Starred):
        names.extend(_flat_names(node.value))
    return names


def _reads(node: ast.AST, tainted: Set[str]) -> bool:
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                and sub.id in tainted):
            return True
    return False


class DonationRule(Rule):
    id = "TH-DON"
    title = "donated buffer not aliased into a return / used after donation"
    rationale = ("A donated operand must flow into every return path "
                 "(donation = input-output aliasing) and must never be "
                 "read again after the dispatch that consumed it.")
    scope = ("tensorhive_tpu/", "tools/", "bench.py")

    def check(self, module: ModuleContext) -> List[Finding]:
        flow = module.dataflow
        findings: List[Finding] = []
        for wrapper in flow.jit_wrappers.values():
            if not wrapper.has_donation():
                continue
            findings.extend(self._check_return_aliasing(module, flow,
                                                        wrapper))
            findings.extend(self._check_use_after_donate(module, flow,
                                                         wrapper))
        return findings

    # -- every return path must carry the donated value --------------------
    def _check_return_aliasing(self, module: ModuleContext, flow: Dataflow,
                               wrapper: JitWrapper) -> List[Finding]:
        fn = flow.target_function(wrapper)
        if fn is None:
            return []
        findings: List[Finding] = []
        for param in sorted(flow.donated_params(wrapper)):
            tainted = self._taint(fn, param)
            for ret in ast.walk(fn):
                if not isinstance(ret, ast.Return):
                    continue
                if flow.enclosing_function(ret) is not fn:
                    continue
                if ret.value is not None and _reads(ret.value, tainted):
                    continue
                findings.append(Finding(
                    self.id, module.relpath, ret.lineno,
                    f"donated parameter {param!r} of jit target "
                    f"{fn.name}() does not flow into this return — XLA "
                    "cannot alias the buffer and the donation is wasted; "
                    "return the updated value (PR 3's whole-carry rule)"))
        return findings

    @staticmethod
    def _taint(fn: ast.AST, param: str) -> Set[str]:
        """Names derived from ``param`` via assignments, to fixpoint."""
        tainted: Set[str] = {param}
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                targets = _assign_targets(node)
                if not targets:
                    continue
                value = getattr(node, "value", None)
                if value is None or not _reads(value, tainted):
                    continue
                for target in targets:
                    for name in _flat_names(target):
                        if name not in tainted:
                            tainted.add(name)
                            changed = True
        return tainted

    # -- no reads after a donating dispatch --------------------------------
    def _check_use_after_donate(self, module: ModuleContext, flow: Dataflow,
                                wrapper: JitWrapper) -> List[Finding]:
        donated_positions = flow.donated_positions(wrapper)
        if not donated_positions:
            return []
        findings: List[Finding] = []
        for call in flow.call_sites(wrapper.name):
            fn = flow.enclosing_function(call)
            if fn is None:
                continue
            for position, param in sorted(donated_positions.items()):
                arg = call_argument(call, position, param)
                if arg is None:
                    continue
                spelled = dotted_source(arg)
                if spelled is None:
                    continue    # derived expression: nothing nameable dies
                if self._rebound_from_result(module, call, spelled):
                    continue
                if self._dispatched_in_return(module, flow, call, fn):
                    continue    # `return wrapper(...)`: nothing after is
                                # reachable on this path
                later = self._later_read(module, flow, fn, call, spelled)
                if later is not None:
                    findings.append(Finding(
                        self.id, module.relpath, later.lineno,
                        f"{spelled} is read after being passed in donated "
                        f"position {param!r} to {wrapper.name}() (line "
                        f"{call.lineno}) — the buffer is dead after "
                        "dispatch; rebind it from the call's result "
                        "first"))
        return findings

    @staticmethod
    def _dispatched_in_return(module: ModuleContext, flow: Dataflow,
                              call: ast.Call, fn: ast.AST) -> bool:
        for ancestor in module.ancestors(call):
            if ancestor is fn:
                return False
            if isinstance(ancestor, ast.Return):
                return True
        return False

    @staticmethod
    def _rebound_from_result(module: ModuleContext, call: ast.Call,
                             spelled: str) -> bool:
        """``x = wrapper(..., x, ...)`` (possibly through tuple targets):
        the donated operand is immediately replaced by the result."""
        parent = module.parents.get(id(call))
        while isinstance(parent, (ast.Tuple, ast.List)):
            parent = module.parents.get(id(parent))
        if not isinstance(parent, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign, ast.NamedExpr)):
            return False
        for target in _assign_targets(parent):
            stack = [target]
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.Tuple, ast.List)):
                    stack.extend(node.elts)
                elif isinstance(node, ast.Starred):
                    stack.append(node.value)
                elif dotted_source(node) == spelled:
                    return True
        return False

    def _later_read(self, module: ModuleContext, flow: Dataflow,
                    fn: ast.AST, call: ast.Call,
                    spelled: str) -> Optional[ast.AST]:
        """First reachable read of ``spelled`` after the call, unless a
        rebinding comes first. Lexical line order, branch-pruned; a
        rebind wins a same-line tie (``x = f(x)``-shaped statements)."""
        events = []
        in_call = {id(sub) for sub in ast.walk(call)}
        for node in ast.walk(fn):
            lineno = getattr(node, "lineno", None)
            if lineno is None or lineno <= call.lineno:
                continue
            # a multi-line call's own arguments sit on later lines than
            # the call node; they are the dispatch, not a later read
            if id(node) in in_call:
                continue
            if flow.enclosing_function(node) is not fn:
                continue
            if not flow.same_branch(call, node):
                continue
            if any(spelled in self._target_chains(target)
                   for target in _assign_targets(node)):
                events.append((lineno, 0, "rebind", node))
            elif (dotted_source(node) == spelled
                  and isinstance(getattr(node, "ctx", None), ast.Load)):
                # any read counts — x.k on a dead donated x is still a
                # use-after-free of the whole buffer
                events.append((lineno, 1, "read", node))
        for _, _, kind, node in sorted(events, key=lambda e: (e[0], e[1])):
            if kind == "rebind":
                return None
            return node
        return None

    @staticmethod
    def _target_chains(target: ast.AST) -> Set[str]:
        """Dotted spellings of every flat element of an assignment
        target (tuple/list unpacking included)."""
        chains: Set[str] = set()
        stack = [target]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Tuple, ast.List)):
                stack.extend(node.elts)
            elif isinstance(node, ast.Starred):
                stack.append(node.value)
            else:
                spelled = dotted_source(node)
                if spelled is not None:
                    chains.add(spelled)
        return chains


register(DonationRule())
