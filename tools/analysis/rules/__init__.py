"""Rule passes — importing this package registers every rule with the
engine registry (one module per defect family)."""
from . import blocking, concurrency, exceptions, jax_sync, legacy  # noqa: F401
