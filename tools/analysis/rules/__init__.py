"""Rule passes — importing this package registers every rule with the
engine registry (one module per defect family)."""
from . import (  # noqa: F401
    blocking,
    concurrency,
    contracts,
    donation,
    exceptions,
    jax_flow,
    jax_sync,
    legacy,
    locks,
    refcount,
)
