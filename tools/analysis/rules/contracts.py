"""TH-X: cross-artifact contracts — code, docs and UI checked together.

Every observable surface this repo ships is a three-way contract: a metric
is registered in Python, documented in docs/OBSERVABILITY.md, and (for the
serving strip) rendered by the dashboard. Nothing enforced any edge of
that triangle until now — a renamed metric silently orphans its docs row,
a new config knob ships undocumented, the dashboard renders a stats field
the API stopped sending. This pass parses all the artifacts in one run
(it is a :class:`~tools.analysis.engine.ProjectRule` — repo-scoped, runs
even under ``--changed-only``) and checks:

* **metric naming + docs rows, bidirectionally** — every
  ``get_registry().counter/gauge/histogram("tpuhive_*")`` registration
  must follow the documented naming rule (counters end ``_total``;
  nothing else may claim that suffix) and have a row in
  docs/OBSERVABILITY.md's tables; every ``tpuhive_*`` name referenced
  from a docs table row must resolve to a registered metric. Doc rows use
  suffix shorthand (``tpuhive_service_ticks_total`` / ``_tick_failures_
  total``); a shorthand resolves if ANY underscore-boundary prefix of a
  full name in the same row completes it to a registered metric.
* **config knob docs rows** — every field of ``GenerationConfig``
  (``[generation_service]``) has a ``| `knob` |`` row in docs/SERVING.md,
  every ``ProfilingConfig`` (``[profiling]``), ``HistoryConfig``
  (``[history]``) and ``SloConfig`` (``[slo]``) knob appears in
  docs/OBSERVABILITY.md; reverse direction: every key row of SERVING.md's
  config table names a real field.
* **observability endpoints, bidirectionally** — every route the
  observability controller registers has a ``| `METHOD /api/...` |`` row
  in docs/OBSERVABILITY.md, and every row of its ``## Endpoints`` table
  names a route some controller actually registers.
* **SLO objectives vs their table** — every ``SloObjective(name=...)``
  in the default pack (observability/slo.py) has a row in
  docs/OBSERVABILITY.md's objective table (first cell the backticked
  name, second cell the percent target), and every such row names a
  shipped objective.
* **stats schema vs the dashboard** — every ``stats.<key>`` fragment
  nodes.js renders must be a key of ``STATS_SCHEMA``
  (controllers/generate.py) — the exact drift the ui-contract tests pin
  one field at a time, enforced for the whole surface.
* **alert pack vs rule table** — every ``AlertRule(name=...)`` in the
  default pack has a row in a documented rule table, and every rule-table
  row names a rule the pack actually ships.

Findings target the artifact that drifted (the registration line, the
config field line, the docs row line, the nodes.js line). Inline
suppression does not apply (non-Python artifacts); deliberate exceptions
are baseline waivers with written reasons — see the capacity-gauge
waivers in tools/analysis/baseline.json for the worked example.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Sequence, Set, Tuple

from ..engine import Finding, ProjectRule, register

METRIC_KINDS = {"counter", "gauge", "histogram"}
SEVERITIES = {"critical", "warning", "info"}

FULL_METRIC_RE = re.compile(r"tpuhive_[a-z0-9][a-z0-9_]*")
SHORT_METRIC_RE = re.compile(r"`(_[a-z0-9][a-z0-9_]*)(?:\{[^}]*\})?`")
STATS_REF_RE = re.compile(r"\bstats\.([A-Za-z0-9_]+)")
ROW_KEY_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")


class RegisteredMetric:
    __slots__ = ("name", "kind", "path", "line")

    def __init__(self, name: str, kind: str, path: str, line: int) -> None:
        self.name = name
        self.kind = kind
        self.path = path
        self.line = line


def collect_metrics(root: Path) -> List[RegisteredMetric]:
    """Every ``.counter/.gauge/.histogram("tpuhive_*")`` registration under
    ``tensorhive_tpu/`` (AST-exact: literal first argument only)."""
    metrics: List[RegisteredMetric] = []
    package = root / "tensorhive_tpu"
    if not package.is_dir():
        return metrics
    for path in sorted(package.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue        # TH-SYNTAX owns unparseable files
        relpath = path.relative_to(root).as_posix()
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in METRIC_KINDS):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if name.startswith("tpuhive_"):
                metrics.append(RegisteredMetric(name, node.func.attr,
                                                relpath, node.lineno))
    return metrics


def _doc_table_rows(text: str) -> List[Tuple[int, str]]:
    return [(lineno, line) for lineno, line in
            enumerate(text.splitlines(), start=1)
            if line.lstrip().startswith("|")]


def _underscore_prefixes(name: str) -> List[str]:
    parts = name.split("_")
    return ["_".join(parts[:i]) for i in range(1, len(parts))]


def documented_metric_names(text: str) -> Set[str]:
    """Full names + every underscore-boundary shorthand expansion found in
    the doc's table rows (over-generates on purpose: lenient toward the
    code→docs direction, exact enough for docs→code)."""
    documented: Set[str] = set()
    for _, row in _doc_table_rows(text):
        fulls = FULL_METRIC_RE.findall(row)
        documented.update(fulls)
        for short in SHORT_METRIC_RE.findall(row):
            for full in fulls:
                for prefix in _underscore_prefixes(full):
                    documented.add(prefix + short)
    return documented


def doc_metric_references(text: str) -> List[Tuple[int, str, Sequence[str]]]:
    """(line, token, row-full-names) for every metric reference in table
    rows — full names verbatim, shorthands as their ``_suffix`` token."""
    refs: List[Tuple[int, str, Sequence[str]]] = []
    for lineno, row in _doc_table_rows(text):
        fulls = FULL_METRIC_RE.findall(row)
        for full in fulls:
            refs.append((lineno, full, fulls))
        for short in SHORT_METRIC_RE.findall(row):
            refs.append((lineno, short, fulls))
    return refs


def dataclass_fields(tree: ast.AST, class_name: str) -> List[Tuple[str, int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return [(stmt.target.id, stmt.lineno) for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)]
    return []


def stats_schema_keys(tree: ast.AST) -> Set[str]:
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "STATS_SCHEMA"
                and isinstance(node.value, ast.Call)):
            return {kw.arg for kw in node.value.keywords
                    if kw.arg is not None and kw.arg != "required"}
    return set()


def alert_pack_rules(tree: ast.AST) -> List[Tuple[str, int]]:
    rules: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and ((isinstance(node.func, ast.Name)
                      and node.func.id == "AlertRule")
                     or (isinstance(node.func, ast.Attribute)
                         and node.func.attr == "AlertRule"))):
            continue
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                rules.append((kw.value.value, node.lineno))
    return rules


ENDPOINT_ROW_RE = re.compile(r"`(GET|POST|PUT|DELETE|PATCH)\s+(/api/\S+)`")
PERCENT_RE = re.compile(r"^\d+(\.\d+)?\s*%")


def controller_routes(path: Path) -> List[Tuple[str, str, int]]:
    """(method, path, line) for every ``@route("/p", ["GET", ...])``
    decorator in one controller module (literal args only)."""
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return []
    routes: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "route"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and isinstance(node.args[1], (ast.List, ast.Tuple))):
            continue
        for elt in node.args[1].elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                routes.append((elt.value, node.args[0].value, node.lineno))
    return routes


def endpoint_table_rows(text: str) -> List[Tuple[int, str, str]]:
    """(line, method, path) rows of the FIRST table after the
    ``## Endpoints`` heading in docs/OBSERVABILITY.md."""
    rows: List[Tuple[int, str, str]] = []
    in_section = False
    in_table = False
    for lineno, line in enumerate(text.splitlines(), start=1):
        if line.startswith("## "):
            if in_table:
                break
            in_section = line.strip() == "## Endpoints"
            continue
        if not in_section:
            continue
        if line.lstrip().startswith("|"):
            in_table = True
            match = ENDPOINT_ROW_RE.search(line)
            if match:
                rows.append((lineno, match.group(1), match.group(2)))
        elif in_table:
            break               # first table ended
    return rows


def slo_objective_names(tree: ast.AST) -> List[Tuple[str, int]]:
    """Every ``SloObjective(name="...")`` keyword literal — the AST-exact
    twin of :func:`alert_pack_rules` for the SLO pack."""
    names: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and ((isinstance(node.func, ast.Name)
                      and node.func.id == "SloObjective")
                     or (isinstance(node.func, ast.Attribute)
                         and node.func.attr == "SloObjective"))):
            continue
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                names.append((kw.value.value, node.lineno))
    return names


def doc_objective_rows(text: str) -> List[Tuple[int, str]]:
    """(line, objective-name) for SLO objective table rows:
    ``| `name` | NN% ... |`` — a backticked first cell with a
    percent-target second cell (the shape that distinguishes the
    objective table from the rule and metric tables)."""
    rows: List[Tuple[int, str]] = []
    for lineno, row in _doc_table_rows(text):
        cells = [cell.strip() for cell in row.strip().strip("|").split("|")]
        if len(cells) < 2 or not PERCENT_RE.match(cells[1]):
            continue
        match = re.fullmatch(r"`([a-z0-9_]+)`", cells[0])
        if match:
            rows.append((lineno, match.group(1)))
    return rows


def doc_rule_rows(text: str) -> List[Tuple[int, str]]:
    """(line, rule-name) for rule-pack table rows: ``| `name` | severity |``
    where the second cell is a severity word."""
    rows: List[Tuple[int, str]] = []
    for lineno, row in _doc_table_rows(text):
        cells = [cell.strip() for cell in row.strip().strip("|").split("|")]
        if len(cells) < 2 or cells[1] not in SEVERITIES:
            continue
        match = re.fullmatch(r"`([a-z0-9_]+)`", cells[0])
        if match:
            rows.append((lineno, match.group(1)))
    return rows


def section_config_rows(text: str, heading: str) -> List[Tuple[int, str]]:
    """Key rows of the FIRST table after the given ``## `` heading — the
    section's config-knob table."""
    rows: List[Tuple[int, str]] = []
    in_section = False
    in_table = False
    for lineno, line in enumerate(text.splitlines(), start=1):
        if line.startswith("## "):
            if in_table:
                break
            in_section = line.strip() == heading
            continue
        if not in_section:
            continue
        if line.lstrip().startswith("|"):
            in_table = True
            match = ROW_KEY_RE.match(line.strip())
            if match:
                rows.append((lineno, match.group(1)))
        elif in_table:
            break               # first table ended
    return rows


def serving_config_rows(text: str) -> List[Tuple[int, str]]:
    """Key rows of the FIRST table after the ``## Configuration`` heading
    in docs/SERVING.md (the ``[generation_service]`` knob table)."""
    return section_config_rows(text, "## Configuration")


class CrossArtifactRule(ProjectRule):
    id = "TH-X"
    title = "cross-artifact contract drift (code vs docs vs dashboard)"
    rationale = ("Metrics, config knobs, stats fields and alert rules are "
                 "contracts between code, docs and the UI; any edge "
                 "drifting silently strands the other two.")

    def check_project(self, root: Path) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_metrics(root))
        findings.extend(self._check_config_knobs(root))
        findings.extend(self._check_stats_schema(root))
        findings.extend(self._check_alert_rules(root))
        findings.extend(self._check_admin_endpoints(root))
        findings.extend(self._check_slo_objectives(root))
        return findings

    # -- metrics ------------------------------------------------------------
    def _check_metrics(self, root: Path) -> List[Finding]:
        doc_path = root / "docs" / "OBSERVABILITY.md"
        metrics = collect_metrics(root)
        if not metrics or not doc_path.exists():
            return []
        findings: List[Finding] = []
        registered = {m.name for m in metrics}
        kinds: Dict[str, str] = {m.name: m.kind for m in metrics}
        doc_text = doc_path.read_text()
        documented = documented_metric_names(doc_text)
        for metric in metrics:
            if metric.kind == "counter" and not metric.name.endswith("_total"):
                findings.append(Finding(
                    self.id, metric.path, metric.line,
                    f"counter {metric.name} must end _total "
                    "(docs/OBSERVABILITY.md naming rule: "
                    "tpuhive_<subsystem>_<what>_<unit>)"))
            if metric.kind != "counter" and metric.name.endswith("_total"):
                findings.append(Finding(
                    self.id, metric.path, metric.line,
                    f"{metric.kind} {metric.name} ends _total, the suffix "
                    "reserved for counters — rate()/increase() over a "
                    "gauge silently lies on dashboards"))
            if metric.name not in documented:
                findings.append(Finding(
                    self.id, metric.path, metric.line,
                    f"registered metric {metric.name} has no row in "
                    "docs/OBSERVABILITY.md — every exported series needs "
                    "its operator contract documented"))
        doc_rel = doc_path.relative_to(root).as_posix()
        seen: Set[Tuple[int, str]] = set()
        for lineno, token, fulls in doc_metric_references(doc_text):
            if token.startswith("tpuhive_"):
                resolved = token in registered
            else:
                resolved = any(prefix + token in registered
                               for full in fulls
                               for prefix in _underscore_prefixes(full))
            if not resolved and (lineno, token) not in seen:
                seen.add((lineno, token))
                findings.append(Finding(
                    self.id, doc_rel, lineno,
                    f"docs row references metric {token!r} but no such "
                    "metric is registered — the docs drifted from the "
                    "code (or the row's shorthand no longer expands to a "
                    "real name)"))
        _ = kinds
        return findings

    # -- config knobs --------------------------------------------------------
    def _check_config_knobs(self, root: Path) -> List[Finding]:
        config_path = root / "tensorhive_tpu" / "config.py"
        serving_doc = root / "docs" / "SERVING.md"
        observability_doc = root / "docs" / "OBSERVABILITY.md"
        if not config_path.exists():
            return []
        try:
            tree = ast.parse(config_path.read_text())
        except SyntaxError:
            return []
        findings: List[Finding] = []
        config_rel = config_path.relative_to(root).as_posix()
        if serving_doc.exists():
            text = serving_doc.read_text()
            fields = dataclass_fields(tree, "GenerationConfig")
            field_names = {name for name, _ in fields}
            for name, lineno in fields:
                if not re.search(r"\|\s*`" + re.escape(name) + r"`\s*\|",
                                 text):
                    findings.append(Finding(
                        self.id, config_rel, lineno,
                        f"[generation_service] knob {name!r} has no row in "
                        "docs/SERVING.md's configuration table"))
            doc_rel = serving_doc.relative_to(root).as_posix()
            for lineno, key in serving_config_rows(text):
                if key not in field_names:
                    findings.append(Finding(
                        self.id, doc_rel, lineno,
                        f"docs/SERVING.md documents [generation_service] "
                        f"knob {key!r} but GenerationConfig has no such "
                        "field — the docs drifted from config.py"))
        if observability_doc.exists():
            text = observability_doc.read_text()
            for class_name, section in (("ProfilingConfig", "profiling"),
                                        ("HistoryConfig", "history"),
                                        ("SloConfig", "slo"),
                                        ("AccountingConfig", "accounting")):
                for name, lineno in dataclass_fields(tree, class_name):
                    row = re.search(r"\|\s*`" + re.escape(name) + r"`\s*\|",
                                    text)
                    snippet = re.search(
                        r"^\s*#?\s*" + re.escape(name) + r"\s*=", text,
                        flags=re.MULTILINE)
                    if not row and not snippet:
                        findings.append(Finding(
                            self.id, config_rel, lineno,
                            f"[{section}] knob {name!r} is not documented "
                            "in docs/OBSERVABILITY.md (neither a table row "
                            "nor the config snippet)"))
            # reverse direction for the tenant-accounting knob table: a
            # documented [accounting] row with no AccountingConfig field
            # is docs drift (same contract the SERVING.md table enforces
            # for [generation_service])
            accounting_fields = {
                name for name, _ in dataclass_fields(tree,
                                                     "AccountingConfig")}
            doc_rel = observability_doc.relative_to(root).as_posix()
            for lineno, key in section_config_rows(text,
                                                   "## Tenant accounting"):
                if accounting_fields and key not in accounting_fields:
                    findings.append(Finding(
                        self.id, doc_rel, lineno,
                        f"docs/OBSERVABILITY.md documents [accounting] "
                        f"knob {key!r} but AccountingConfig has no such "
                        "field — the docs drifted from config.py"))
        return findings

    # -- stats schema vs dashboard ------------------------------------------
    def _check_stats_schema(self, root: Path) -> List[Finding]:
        schema_path = root / "tensorhive_tpu" / "controllers" / "generate.py"
        ui_path = root / "tensorhive_tpu" / "app" / "static" / "js" / \
            "nodes.js"
        if not schema_path.exists() or not ui_path.exists():
            return []
        try:
            tree = ast.parse(schema_path.read_text())
        except SyntaxError:
            return []
        keys = stats_schema_keys(tree)
        if not keys:
            return []
        findings: List[Finding] = []
        ui_rel = ui_path.relative_to(root).as_posix()
        for lineno, line in enumerate(ui_path.read_text().splitlines(),
                                      start=1):
            for key in STATS_REF_RE.findall(line):
                if key not in keys:
                    findings.append(Finding(
                        self.id, ui_rel, lineno,
                        f"nodes.js renders stats.{key} but STATS_SCHEMA "
                        "(controllers/generate.py) has no such key — the "
                        "dashboard fragment would render undefined"))
        return findings

    # -- alert pack vs rule table -------------------------------------------
    def _check_alert_rules(self, root: Path) -> List[Finding]:
        alerts_path = root / "tensorhive_tpu" / "observability" / "alerts.py"
        docs = [root / "docs" / "OBSERVABILITY.md",
                root / "docs" / "SERVING.md"]
        docs = [d for d in docs if d.exists()]
        if not alerts_path.exists() or not docs:
            return []
        try:
            tree = ast.parse(alerts_path.read_text())
        except SyntaxError:
            return []
        pack = alert_pack_rules(tree)
        if not pack:
            return []
        pack_names = {name for name, _ in pack}
        documented: Set[str] = set()
        row_refs: List[Tuple[Path, int, str]] = []
        for doc in docs:
            for lineno, name in doc_rule_rows(doc.read_text()):
                documented.add(name)
                row_refs.append((doc, lineno, name))
        findings: List[Finding] = []
        alerts_rel = alerts_path.relative_to(root).as_posix()
        for name, lineno in pack:
            if name not in documented:
                findings.append(Finding(
                    self.id, alerts_rel, lineno,
                    f"alert rule {name!r} ships in the default pack but "
                    "has no row in the documented rule table "
                    "(docs/OBSERVABILITY.md)"))
        for doc, lineno, name in row_refs:
            if name not in pack_names:
                findings.append(Finding(
                    self.id, doc.relative_to(root).as_posix(), lineno,
                    f"rule table documents {name!r} but the default alert "
                    "pack ships no rule by that name — the docs drifted "
                    "from observability/alerts.py"))
        return findings

    # -- observability endpoints vs docs table ------------------------------
    def _check_admin_endpoints(self, root: Path) -> List[Finding]:
        controllers = root / "tensorhive_tpu" / "controllers"
        obs_controller = controllers / "observability.py"
        doc_path = root / "docs" / "OBSERVABILITY.md"
        if not obs_controller.exists() or not doc_path.exists():
            return []
        obs_routes = controller_routes(obs_controller)
        if not obs_routes:
            return []
        text = doc_path.read_text()
        doc_rows = endpoint_table_rows(text)
        documented = {(method, path) for _, method, path in doc_rows}
        findings: List[Finding] = []
        obs_rel = obs_controller.relative_to(root).as_posix()
        for method, path, lineno in obs_routes:
            if (method, "/api" + path) not in documented:
                findings.append(Finding(
                    self.id, obs_rel, lineno,
                    f"observability endpoint {method} /api{path} has no "
                    "row in docs/OBSERVABILITY.md's endpoint table — "
                    "every operator surface needs its contract "
                    "documented"))
        registered = {(method, "/api" + path)
                      for controller in sorted(controllers.glob("*.py"))
                      for method, path, _ in controller_routes(controller)}
        doc_rel = doc_path.relative_to(root).as_posix()
        for lineno, method, path in doc_rows:
            if (method, path) not in registered:
                findings.append(Finding(
                    self.id, doc_rel, lineno,
                    f"endpoint table documents {method} {path} but no "
                    "controller registers that route — the docs drifted "
                    "from the code"))
        return findings

    # -- SLO objective pack vs objective table ------------------------------
    def _check_slo_objectives(self, root: Path) -> List[Finding]:
        slo_path = root / "tensorhive_tpu" / "observability" / "slo.py"
        doc_path = root / "docs" / "OBSERVABILITY.md"
        if not slo_path.exists() or not doc_path.exists():
            return []
        try:
            tree = ast.parse(slo_path.read_text())
        except SyntaxError:
            return []
        pack = slo_objective_names(tree)
        if not pack:
            return []
        text = doc_path.read_text()
        rows = doc_objective_rows(text)
        documented = {name for _, name in rows}
        pack_names = {name for name, _ in pack}
        findings: List[Finding] = []
        slo_rel = slo_path.relative_to(root).as_posix()
        for name, lineno in pack:
            if name not in documented:
                findings.append(Finding(
                    self.id, slo_rel, lineno,
                    f"SLO objective {name!r} ships in the default pack "
                    "but has no row in docs/OBSERVABILITY.md's objective "
                    "table"))
        doc_rel = doc_path.relative_to(root).as_posix()
        for lineno, name in rows:
            if name not in pack_names:
                findings.append(Finding(
                    self.id, doc_rel, lineno,
                    f"objective table documents {name!r} but the default "
                    "SLO pack ships no objective by that name — the docs "
                    "drifted from observability/slo.py"))
        return findings


register(CrossArtifactRule())
