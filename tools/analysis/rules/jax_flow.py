"""TH-JIT: recompile hazards around jit wrappers (flow-aware).

The serving data plane's whole performance story rests on "one executable,
forever": per-slot state, page tables and positions are TRACED operands;
everything shape- or dispatch-determining is STATIC and constant for the
engine's lifetime (serving/engine.py). The quiet way to lose that is at a
CALL SITE — a value that varies per request or per iteration flowing into a
static position mints a new executable per distinct value, and nothing
crashes: latency just collapses one compile at a time. Three shapes, all
resolved through the shared dataflow layer (``ModuleContext.dataflow`` —
``jax.jit(f, ...)`` / ``functools.partial(jax.jit, ...)(f)`` assignments
and jit decorators are recognized alike):

* **loop-varying static argument** — a call to a known jit wrapper inside
  a ``for``/``while`` loop passing a name that is (re)bound inside that
  loop in a static position. One recompile per distinct value; inside a
  request loop, one per request.
* **host branch on a traced parameter** — ``if``/``while`` on a
  non-static parameter inside a jit target's body either raises
  ``TracerBoolConversionError`` or silently bakes one branch into the
  compiled program. ``x is None`` tests and ``.shape``/``.dtype``/
  ``.ndim``/``.size`` accesses are trace-time facts and exempt.
* **unfingerprinted serving dispatch** — in ``tensorhive_tpu/serving/``,
  every direct call to a jit wrapper must sit in a function that also
  routes through the ``_count_compile`` fingerprint seam
  (``tpuhive_decode_compile_total`` — docs/OBSERVABILITY.md): a dispatch
  the counter cannot see is a recompile the zero-recompile gates cannot
  catch.

Lexical and module-flat like the rest of the gate: wrappers called through
locals/imports are not chased.
"""
from __future__ import annotations

import ast
from typing import List, Set

from ..dataflow import Dataflow, JitWrapper, call_argument
from ..engine import Finding, ModuleContext, Rule, register

#: attribute reads on a traced value that are trace-time constants
SHAPE_ATTRS = {"shape", "dtype", "ndim", "size"}

#: functions in serving/ that ARE the fingerprint seam (calling one of
#: these before the dispatch satisfies the contract)
COMPILE_SEAM_MARKERS = ("count_compile", "count_prefill_compile",
                        "count_chunk_prefill_compile")


def _reads_seam(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            func = node.func
            name = (func.id if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute)
                    else "")
            if any(marker in name for marker in COMPILE_SEAM_MARKERS):
                return True
    return False


class JitRecompileRule(Rule):
    id = "TH-JIT"
    title = "recompile hazard at a jit wrapper (static-arg flow / traced branch / unfingerprinted dispatch)"
    rationale = ("A per-iteration value in a static position or a host "
                 "branch on a traced param silently mints one executable "
                 "per distinct value — the zero-recompile contract dies "
                 "without a crash.")
    scope = ("tensorhive_tpu/", "tools/", "bench.py")

    def check(self, module: ModuleContext) -> List[Finding]:
        flow = module.dataflow
        findings: List[Finding] = []
        for wrapper in flow.jit_wrappers.values():
            findings.extend(self._check_traced_branches(module, flow,
                                                        wrapper))
            findings.extend(self._check_call_sites(module, flow, wrapper))
        if module.relpath.startswith("tensorhive_tpu/serving/"):
            findings.extend(self._check_fingerprint_seam(module, flow))
        return findings

    # -- loop-varying static args ------------------------------------------
    def _check_call_sites(self, module: ModuleContext, flow: Dataflow,
                          wrapper: JitWrapper) -> List[Finding]:
        static_positions = flow.static_positions(wrapper)
        if not static_positions:
            return []
        findings: List[Finding] = []
        for call in flow.call_sites(wrapper.name):
            loops = flow.enclosing_loops(call)
            if not loops:
                continue
            loop_bound: Set[str] = set()
            for loop in loops:
                loop_bound |= Dataflow.bound_in(loop)
            for position, param in static_positions.items():
                arg = call_argument(call, position, param)
                if isinstance(arg, ast.Name) and arg.id in loop_bound:
                    findings.append(Finding(
                        self.id, module.relpath, call.lineno,
                        f"loop-varying value {arg.id!r} flows into static "
                        f"position {param!r} of jit-wrapped "
                        f"{wrapper.name}() — one recompile per distinct "
                        "value; make it a traced operand or hoist it out "
                        "of the loop"))
        return findings

    # -- host branches on traced params ------------------------------------
    def _check_traced_branches(self, module: ModuleContext, flow: Dataflow,
                               wrapper: JitWrapper) -> List[Finding]:
        fn = flow.target_function(wrapper)
        if fn is None:
            return []
        params = flow.target_params(wrapper)
        statics = flow.static_params(wrapper)
        traced = [p for p in params if p not in statics and p != "self"]
        if not traced:
            return []
        findings: List[Finding] = []
        seen: Set[tuple] = set()
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            # branches inside nested defs belong to the nested function
            # (helpers are dispatched traced, e.g. closure attends)
            if flow.enclosing_function(node) is not fn:
                continue
            for name in self._traced_reads(module, node.test, traced):
                if (node.lineno, name) in seen:
                    continue
                seen.add((node.lineno, name))
                findings.append(Finding(
                    self.id, module.relpath, node.lineno,
                    f"host-Python branch on traced parameter {name!r} "
                    f"inside jit target {fn.name}() — fails to trace or "
                    "bakes one branch into the executable; use jnp.where/"
                    "lax.cond, or declare it static"))
        return findings

    def _traced_reads(self, module: ModuleContext, test: ast.AST,
                      traced: List[str]) -> List[str]:
        names: List[str] = []
        for node in ast.walk(test):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in traced):
                continue
            parent = module.parents.get(id(node))
            # trace-time facts: x.shape / x.dtype / x is None / x is not y
            if isinstance(parent, ast.Attribute) and \
                    parent.attr in SHAPE_ATTRS:
                continue
            if isinstance(parent, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in parent.ops):
                continue
            # len(x) on a traced array is a shape fact too
            if (isinstance(parent, ast.Call)
                    and isinstance(parent.func, ast.Name)
                    and parent.func.id == "len"):
                continue
            names.append(node.id)
        return names

    # -- serving fingerprint seam ------------------------------------------
    def _check_fingerprint_seam(self, module: ModuleContext,
                                flow: Dataflow) -> List[Finding]:
        findings: List[Finding] = []
        for wrapper in flow.jit_wrappers.values():
            for call in flow.call_sites(wrapper.name):
                fn = flow.enclosing_function(call)
                if fn is None:
                    continue        # module-level warmup/bench dispatch
                if _reads_seam(fn):
                    continue
                findings.append(Finding(
                    self.id, module.relpath, call.lineno,
                    f"serving dispatch of jit-wrapped {wrapper.name}() in "
                    f"{fn.name}() is not routed through the _count_compile "
                    "fingerprint seam — its compiles are invisible to "
                    "tpuhive_decode_compile_total and the zero-recompile "
                    "gates"))
        return findings


register(JitRecompileRule())
