"""TH-LOCK: interprocedural deadlock and lock-order analysis.

TH-C sees one function at a time; the defects that actually hang a
control plane live in the composition. This family builds per-function
*lock summaries* — which locks a function acquires (``with self._lock`` /
``.acquire()``), which calls it makes while holding them, which blocking
operations it performs — and propagates them over the repo call graph
(tools/analysis/callgraph.py) into a global lock-acquisition-order graph.
Four checks:

* **(a) order-inversion cycle** — two (or more) distinct locks acquired
  in opposite orders on different paths. Each cycle is a potential
  deadlock the moment both paths run concurrently; the finding names the
  full cycle with one example site per edge.
* **(b) blocking call while a lock is held, transitively** — ``time.sleep``,
  subprocess, transport fan-out without timeout, zero-arg ``.join()`` /
  ``.wait()``, DB ``.commit()`` reachable through any call chain from a
  held region. ``cond.wait()`` on the held lock itself is exempt (wait
  releases it — that is the point of a condition variable).
* **(c) user-callback / sink invocation under a lock** — calling a
  configured callable (``rule.source()``, ``sink.notify()``, a callable
  parameter) while holding a lock hands YOUR lock to code you don't
  control. The PR 4 "fan out outside the lock" discipline, now checked.
* **(d) re-acquisition of a non-reentrant Lock through a call chain** —
  ``self.a()`` -> ``self.b()`` -> ``with self._lock`` while ``a`` already
  holds it: self-deadlock. Class locks are chased only through
  ``self.*``-rooted chains (same instance, provably the same lock);
  module-level locks through any chain (one object).

The ``*_locked`` convention (dataflow.is_locked_name, shared with
TH-C/TH-REF) is modeled as *caller holds the class lock*: a ``*_locked``
body's calls count as made under the lock, but the method itself acquires
nothing.

The static model's honesty is checked at runtime: the lockwitness
(tensorhive_tpu/utils/lockwitness.py) records the observed-order graph
under ``TPUHIVE_LOCK_WITNESS=1`` and ``python -m tools.analysis --witness
<dump>`` asserts observed edges are a subset of this rule's graph.
"""
from __future__ import annotations

import ast
import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import CallGraph, FunctionInfo, LockDecl, get_callgraph
from ..dataflow import dotted_source, self_attr
from ..engine import Finding, ProjectRule, register

SUBPROCESS_CALLS = {"run", "call", "check_call", "check_output", "Popen"}
TRANSPORT_CALLS = {"run_on_all", "check_output"}

#: attribute spellings that invoke configured/user code (alert sources,
#: notification sinks, generic callbacks)
CALLBACK_ATTRS = {"source", "notify", "callback"}

#: callable parameters that are injected *time sources*, not user code —
#: calling the clock under a lock is fine (an injected ``sleep`` is
#: blocking, not a callback: check (b) owns it)
TIME_SOURCE_PARAMS = {"clock", "now", "time_source", "timer", "sleep"}

#: the registry family-lock witness name every wait-export observation
#: ultimately acquires (see lockwitness: the wait histogram's children
#: share their family's lock)
WAIT_EXPORT_LOCK = "MetricFamily._lock"


@dataclasses.dataclass(frozen=True)
class BlockingSite:
    desc: str
    relpath: str
    lineno: int
    receiver: str       # lexical receiver spelling ("" when none)
    is_wait: bool = False


@dataclasses.dataclass(frozen=True)
class CallbackSite:
    desc: str
    relpath: str
    lineno: int


@dataclasses.dataclass(frozen=True)
class EdgeSite:
    relpath: str
    lineno: int
    holder: str         # function display name where the edge is created
    via: str            # callee display the acquisition happens through


Held = Tuple[Tuple[LockDecl, str], ...]     # ((decl, spelling), ...)


@dataclasses.dataclass
class Summary:
    info: FunctionInfo
    direct_acquires: Set[str] = dataclasses.field(default_factory=set)
    # (node, acquired decl keys, held) for every acquisition site
    acquire_sites: List[Tuple[ast.AST, Set[str], Held]] = \
        dataclasses.field(default_factory=list)
    # (node, callee qnames, held, is_self_call)
    call_sites: List[Tuple[ast.AST, Set[str], Held, bool]] = \
        dataclasses.field(default_factory=list)
    # (node, @property getter qnames, held) — kept apart from call_sites
    # so check (d) can ignore them: a property NAME match is too weak
    # evidence for "same instance, same lock" (config.history is not
    # SloEngine.history)
    property_sites: List[Tuple[ast.AST, Set[str], Held]] = \
        dataclasses.field(default_factory=list)
    blocking: List[Tuple[BlockingSite, Held]] = \
        dataclasses.field(default_factory=list)
    callbacks: List[Tuple[CallbackSite, Held]] = \
        dataclasses.field(default_factory=list)
    self_callees: Set[str] = dataclasses.field(default_factory=set)


class LockModel:
    """Summaries + fixpoints + the global lock-order graph for one root."""

    def __init__(self, cg: CallGraph) -> None:
        self.cg = cg
        self.summaries: Dict[str, Summary] = {}
        for qname, info in cg.functions.items():
            self.summaries[qname] = self._summarize(info)
        # call edges for the fixpoints: the call graph's resolved calls
        # plus @property loads (a property read is a call in disguise)
        self.call_edges: Dict[str, Set[str]] = {}
        for qname, summary in self.summaries.items():
            callees = set(cg.edges.get(qname, set()))
            for _node, site_callees, _held, _is_self in summary.call_sites:
                callees.update(site_callees)
            for _node, site_callees, _held in summary.property_sites:
                callees.update(site_callees)
            self.call_edges[qname] = callees
        self.eff_acquires = self._propagate(
            {q: set(s.direct_acquires) for q, s in self.summaries.items()},
            self.call_edges)
        self.eff_self_acquires = self._fixpoint_self_acquires()
        self.eff_blocking = self._fixpoint_sites(
            lambda s: {site for site, _held in s.blocking})
        self.eff_callbacks = self._fixpoint_sites(
            lambda s: {site for site, _held in s.callbacks})
        #: (from key, to key) -> first example EdgeSite
        self.edges: Dict[Tuple[str, str], EdgeSite] = {}
        self._build_edges()

    # -- per-function summaries --------------------------------------------
    def _summarize(self, info: FunctionInfo) -> Summary:
        summary = Summary(info)
        acquire_regions = self._acquire_regions(info)
        for node in ast.walk(info.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    decls, _sp = self._lock_expr(info, item.context_expr)
                    if decls:
                        held = self._held_at(info, node, acquire_regions)
                        summary.direct_acquires.update(
                            d.key for d in decls)
                        summary.acquire_sites.append(
                            (node, {d.key for d in decls}, held))
            elif isinstance(node, ast.Call):
                self._summarize_call(info, node, summary, acquire_regions)
        for _lineno, decls, _end in acquire_regions:
            summary.direct_acquires.update(d.key for d in decls)
        self._property_sites(info, summary, acquire_regions)
        return summary

    def _summarize_call(self, info: FunctionInfo, node: ast.Call,
                        summary: Summary, acquire_regions) -> None:
        cg = self.cg
        func = node.func
        held = self._held_at(info, node, acquire_regions)
        held_spellings = {sp for _d, sp in held}
        attr_name = func.attr if isinstance(func, ast.Attribute) else None
        receiver = dotted_source(func.value) or "" \
            if isinstance(func, ast.Attribute) else ""

        # explicit .acquire(): an acquisition site (region handled above)
        if attr_name == "acquire":
            decls, _sp = self._lock_expr(info, func.value)
            if decls:
                summary.acquire_sites.append(
                    (node, {d.key for d in decls}, held))
            return
        if attr_name == "release":
            return

        blocking = self._blocking_desc(info, node, receiver)
        if blocking is not None:
            summary.blocking.append((blocking, held))

        callback = self._callback_desc(info, node, receiver, attr_name)
        if callback is not None:
            summary.callbacks.append((callback, held))

        callees = cg.resolve_call(info, node)
        is_self = (isinstance(func, ast.Attribute)
                   and isinstance(func.value, ast.Name)
                   and func.value.id == "self")
        if is_self:
            summary.self_callees.update(callees)
        if callees:
            summary.call_sites.append((node, callees, held, is_self))

    def _property_sites(self, info: FunctionInfo, summary: Summary,
                        acquire_regions) -> None:
        parents = info.module.parents
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Attribute) \
                    or not isinstance(node.ctx, ast.Load):
                continue
            parent = parents.get(id(node))
            if isinstance(parent, ast.Call) and parent.func is node:
                continue        # a method call, handled as a call
            props = self.cg.resolve_property_load(node.attr)
            if not props:
                continue
            held = self._held_at(info, node, acquire_regions)
            summary.property_sites.append((node, props, held))

    def _acquire_regions(self, info: FunctionInfo):
        """(start lineno, decls, end lineno) for explicit ``.acquire()``
        calls, closed by the matching-spelling ``.release()`` (or function
        end). Lexical lineno ranges — the repo overwhelmingly uses
        ``with``; this exists so the few explicit acquires aren't
        invisible."""
        regions = []
        releases: Dict[str, int] = {}
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                spelling = dotted_source(node.func.value) or ""
                if node.func.attr == "release":
                    releases[spelling] = max(releases.get(spelling, 0),
                                             node.lineno)
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                decls, spelling = self._lock_expr(info, node.func.value)
                if decls:
                    end = releases.get(spelling or "", 10 ** 9)
                    regions.append((node.lineno, decls, end))
        return regions

    def _lock_expr(self, info: FunctionInfo,
                   expr: ast.AST) -> Tuple[Set[LockDecl], Optional[str]]:
        attr = self_attr(expr)
        if attr is not None and info.cls:
            decls = self.cg.acquire_targets(info.relpath, info.cls, attr)
            return decls, f"self.{attr}"
        if isinstance(expr, ast.Name):
            decl = self.cg.module_lock(info.relpath, expr.id)
            if decl is not None:
                return {decl}, expr.id
        return set(), None

    def _held_at(self, info: FunctionInfo, node: ast.AST,
                 acquire_regions) -> Held:
        held: List[Tuple[LockDecl, str]] = []
        for ancestor in info.module.ancestors(node):
            if ancestor is info.node:
                break
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                return ()       # nested def: runs with its own held-set
            if isinstance(ancestor, (ast.With, ast.AsyncWith)):
                for item in ancestor.items:
                    decls, spelling = self._lock_expr(info,
                                                      item.context_expr)
                    for decl in decls:
                        held.append((decl, spelling or decl.attr))
        lineno = getattr(node, "lineno", 0)
        for start, decls, end in acquire_regions:
            if start < lineno <= end:
                for decl in decls:
                    held.append((decl, f"self.{decl.attr}"))
        for decl in self.cg.convention_locks(info):
            held.append((decl, f"self.{decl.attr}"))
        return tuple(held)

    def _blocking_desc(self, info: FunctionInfo, node: ast.Call,
                       receiver: str) -> Optional[BlockingSite]:
        func = node.func
        rel, line = info.relpath, node.lineno
        if isinstance(func, ast.Name) and func.id == "sleep":
            return BlockingSite("sleep() (injected sleep callable)", rel,
                                line, "")
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
        if receiver == "time" and attr == "sleep":
            return BlockingSite("time.sleep()", rel, line, receiver)
        if receiver == "subprocess" and attr in SUBPROCESS_CALLS \
                and not has_timeout:
            return BlockingSite(f"subprocess.{attr}() without timeout=",
                                rel, line, receiver)
        if attr in TRANSPORT_CALLS and not has_timeout:
            return BlockingSite(f".{attr}() without timeout=", rel, line,
                                receiver)
        if attr == "join" and not node.args and not node.keywords:
            return BlockingSite(".join() without timeout", rel, line,
                                receiver)
        if attr == "wait" and not node.args and not has_timeout:
            return BlockingSite(".wait() without timeout", rel, line,
                                receiver, is_wait=True)
        if attr == "commit" and not node.args:
            return BlockingSite(".commit()", rel, line, receiver)
        if attr == "urlopen":
            return BlockingSite("urlopen()", rel, line, receiver)
        return None

    def _callback_desc(self, info: FunctionInfo, node: ast.Call,
                       receiver: str,
                       attr_name: Optional[str]) -> Optional[CallbackSite]:
        func = node.func
        if attr_name in CALLBACK_ATTRS:
            # notifying a held Condition is lock API, not a user callback
            decls, _sp = self._lock_expr(info, func.value)
            if decls:
                return None
            return CallbackSite(f"{receiver}.{attr_name}()", info.relpath,
                                node.lineno)
        if isinstance(func, ast.Name):
            params = self._param_names(info)
            if func.id in params and func.id not in TIME_SOURCE_PARAMS \
                    and not self.cg.resolve_call(info, node):
                return CallbackSite(f"{func.id}() (callable parameter)",
                                    info.relpath, node.lineno)
        return None

    @staticmethod
    def _param_names(info: FunctionInfo) -> Set[str]:
        args = info.node.args
        return {a.arg for a in args.posonlyargs + args.args
                + args.kwonlyargs}

    # -- fixpoints ----------------------------------------------------------
    def _fixpoint_self_acquires(self) -> Dict[str, Set[str]]:
        """Lock keys reachable through ``self.*``-rooted chains only —
        the same-instance closure check (d) needs (own-class ``with
        self.X`` acquires, chased through self-calls)."""
        eff = {}
        for qname, summary in self.summaries.items():
            own = set()
            for _node, keys, _held in summary.acquire_sites:
                for key in keys:
                    decl = self.cg.locks.get(key)
                    if decl is not None and decl.owner:
                        own.add(key)
            eff[qname] = own
        self_edges = {q: s.self_callees for q, s in self.summaries.items()}
        return self._propagate(eff, self_edges)

    def _fixpoint_sites(self, direct):
        """Propagate site-sets (blocking / callback) up the call graph,
        remembering one ``via`` callee per inherited site for the
        human-readable chain in the finding."""
        eff: Dict[str, Dict[object, Optional[str]]] = {
            q: {site: None for site in direct(s)}
            for q, s in self.summaries.items()}
        changed = True
        while changed:
            changed = False
            for qname, callees in self.call_edges.items():
                mine = eff.setdefault(qname, {})
                for callee in callees:
                    for site in eff.get(callee, {}):
                        if site not in mine:
                            mine[site] = callee
                            changed = True
        return eff

    @staticmethod
    def _propagate(eff: Dict[str, Set[str]],
                   edges: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
        changed = True
        while changed:
            changed = False
            for qname, callees in edges.items():
                mine = eff.setdefault(qname, set())
                before = len(mine)
                for callee in callees:
                    mine.update(eff.get(callee, set()))
                if len(mine) != before:
                    changed = True
        return eff

    # -- the global order graph --------------------------------------------
    def _build_edges(self) -> None:
        for qname, summary in self.summaries.items():
            info = summary.info
            for node, keys, held in summary.acquire_sites:
                held_keys = {d.key for d, _sp in held}
                for decl_key in keys:
                    if decl_key in held_keys:
                        # re-acquiring a lock this thread already holds
                        # imposes no NEW ordering (the runtime witness
                        # skips these the same way); check (d) owns the
                        # non-reentrant variant
                        continue
                    for held_decl, _sp in held:
                        self._add_edge(held_decl.key, decl_key,
                                       EdgeSite(info.relpath,
                                                node.lineno,
                                                info.display, ""))
            sites = ([(n, c, h) for n, c, h, _s in summary.call_sites]
                     + summary.property_sites)
            for node, callees, held in sites:
                if not held:
                    continue
                held_keys = {d.key for d, _sp in held}
                for callee in callees:
                    for key in self.eff_acquires.get(callee, set()):
                        if key in held_keys:
                            continue    # reentrant re-acquire: no ordering
                        for held_decl, _sp in held:
                            self._add_edge(
                                held_decl.key, key,
                                EdgeSite(info.relpath, node.lineno,
                                         info.display,
                                         self._display(callee)))
        # wait-export: observing a contended acquire of an exported lock
        # touches the wait histogram's family lock while the acquired (and
        # any already-held) witnessed locks are held
        export_target = None
        for decl in self.cg.locks.values():
            if decl.witness_name == WAIT_EXPORT_LOCK:
                export_target = decl
                break
        if export_target is not None:
            for decl in self.cg.locks.values():
                if decl.named and decl.key != export_target.key:
                    self.edges.setdefault(
                        (decl.key, export_target.key),
                        EdgeSite(decl.relpath, decl.lineno, "(wait export)",
                                 "lockwitness wait histogram"))

    def _add_edge(self, from_key: str, to_key: str, site: EdgeSite) -> None:
        self.edges.setdefault((from_key, to_key), site)

    def _display(self, qname: str) -> str:
        info = self.cg.functions.get(qname)
        return info.display if info is not None else qname

    # -- comparator surface -------------------------------------------------
    def witness_names(self) -> Set[str]:
        return {decl.witness_name for decl in self.cg.locks.values()}

    def witness_edges(self) -> Set[Tuple[str, str]]:
        out = set()
        for (k1, k2) in self.edges:
            d1, d2 = self.cg.locks.get(k1), self.cg.locks.get(k2)
            if d1 is not None and d2 is not None \
                    and d1.witness_name != d2.witness_name:
                out.add((d1.witness_name, d2.witness_name))
        return out

    # -- checks -------------------------------------------------------------
    def findings(self, rule_id: str) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_cycles(rule_id))
        findings.extend(self._check_blocking(rule_id))
        findings.extend(self._check_callbacks(rule_id))
        findings.extend(self._check_reacquire(rule_id))
        return findings

    def _lock_name(self, key: str) -> str:
        decl = self.cg.locks.get(key)
        return decl.witness_name if decl is not None else key

    # (a) order-inversion cycles
    def _check_cycles(self, rule_id: str) -> List[Finding]:
        adjacency: Dict[str, Set[str]] = {}
        for (k1, k2) in self.edges:
            adjacency.setdefault(k1, set()).add(k2)
        findings = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        for start in sorted(adjacency):
            cycle = self._shortest_cycle(adjacency, start)
            if cycle is None:
                continue
            canon = self._canonical(cycle)
            if canon in seen_cycles:
                continue
            seen_cycles.add(canon)
            parts = []
            for i, key in enumerate(cycle):
                nxt = cycle[(i + 1) % len(cycle)]
                site = self.edges.get((key, nxt))
                where = f"{site.relpath}:{site.lineno} in {site.holder}" \
                    if site else "?"
                via = f" via {site.via}" if site and site.via else ""
                parts.append(f"{self._lock_name(key)} -> "
                             f"{self._lock_name(nxt)} ({where}{via})")
            first = self.edges.get((cycle[0], cycle[1 % len(cycle)]))
            findings.append(Finding(
                rule_id, first.relpath if first else "",
                first.lineno if first else 0,
                "lock-order inversion (potential deadlock): "
                + "; ".join(parts)
                + " — acquire these locks in one global order, or narrow "
                  "the outer region so the inner lock is taken unheld"))
        return findings

    @staticmethod
    def _canonical(cycle: Tuple[str, ...]) -> Tuple[str, ...]:
        pivot = min(range(len(cycle)), key=lambda i: cycle[i])
        return cycle[pivot:] + cycle[:pivot]

    @staticmethod
    def _shortest_cycle(adjacency: Dict[str, Set[str]],
                        start: str) -> Optional[Tuple[str, ...]]:
        # BFS back to start
        frontier = [(n, (start, n)) for n in sorted(adjacency.get(start,
                                                                  set()))]
        visited = {start}
        while frontier:
            nxt = []
            for node, path in frontier:
                if node == start:
                    return path[:-1]
                if node in visited:
                    continue
                visited.add(node)
                for succ in sorted(adjacency.get(node, set())):
                    nxt.append((succ, path + (succ,)))
            frontier = nxt
        return None

    # (b) blocking reachable while a lock is held
    def _check_blocking(self, rule_id: str) -> List[Finding]:
        findings = []
        reported: Set[Tuple[str, str, int]] = set()
        for qname, summary in self.summaries.items():
            info = summary.info
            for site, held in summary.blocking:
                for decl, spelling in held:
                    if site.is_wait and site.receiver == spelling:
                        continue    # cond.wait releases the lock it guards
                    key = (decl.key, site.relpath, site.lineno)
                    if key in reported:
                        continue
                    reported.add(key)
                    findings.append(Finding(
                        rule_id, site.relpath, site.lineno,
                        f"{site.desc} while holding "
                        f"{self._lock_name(decl.key)} (in {info.display}) "
                        "stalls every thread contending on the lock"))
            sites = ([(n, c, h) for n, c, h, _s in summary.call_sites]
                     + summary.property_sites)
            for node, callees, held in sites:
                if not held:
                    continue
                for callee in callees:
                    for site, via in self.eff_blocking.get(callee,
                                                           {}).items():
                        for decl, _sp in held:
                            key = (decl.key, site.relpath, site.lineno)
                            if key in reported:
                                continue
                            reported.add(key)
                            chain = self._chain(callee, site,
                                                self.eff_blocking)
                            findings.append(Finding(
                                rule_id, info.relpath, node.lineno,
                                f"{site.desc} at {site.relpath}:"
                                f"{site.lineno} is reachable while "
                                f"{info.display} holds "
                                f"{self._lock_name(decl.key)} "
                                f"(call chain {chain})"))
        return findings

    # (c) callback / sink invocation under a lock
    def _check_callbacks(self, rule_id: str) -> List[Finding]:
        findings = []
        reported: Set[Tuple[str, str, int]] = set()
        for qname, summary in self.summaries.items():
            info = summary.info
            for site, held in summary.callbacks:
                if not held:
                    continue
                decl = held[0][0]
                key = (decl.key, site.relpath, site.lineno)
                if key in reported:
                    continue
                reported.add(key)
                findings.append(Finding(
                    rule_id, site.relpath, site.lineno,
                    f"{site.desc} invoked while holding "
                    f"{self._lock_name(decl.key)} (in {info.display}) — "
                    "user/sink code must run outside the lock (snapshot "
                    "under the lock, call after releasing)"))
            sites = ([(n, c, h) for n, c, h, _s in summary.call_sites]
                     + summary.property_sites)
            for node, callees, held in sites:
                if not held:
                    continue
                for callee in callees:
                    for site, _via in self.eff_callbacks.get(callee,
                                                             {}).items():
                        decl = held[0][0]
                        key = (decl.key, site.relpath, site.lineno)
                        if key in reported:
                            continue
                        reported.add(key)
                        chain = self._chain(callee, site,
                                            self.eff_callbacks)
                        findings.append(Finding(
                            rule_id, info.relpath, node.lineno,
                            f"{site.desc} at {site.relpath}:{site.lineno} "
                            f"runs under {self._lock_name(decl.key)} held "
                            f"by {info.display} (call chain {chain}) — "
                            "hoist the callback out of the locked region"))
        return findings

    # (d) re-acquisition of a non-reentrant lock through a call chain
    def _check_reacquire(self, rule_id: str) -> List[Finding]:
        findings = []
        reported: Set[Tuple[str, str, int]] = set()
        for qname, summary in self.summaries.items():
            info = summary.info
            for node, keys, held in summary.acquire_sites:
                for decl_key in keys:
                    decl = self.cg.locks.get(decl_key)
                    if decl is None or decl.reentrant:
                        continue
                    for held_decl, spelling in held:
                        if held_decl.key != decl_key:
                            continue
                        key = (decl_key, info.relpath, node.lineno)
                        if key in reported:
                            continue
                        reported.add(key)
                        findings.append(Finding(
                            rule_id, info.relpath, node.lineno,
                            f"{self._lock_name(decl_key)} re-acquired "
                            f"while already held (in {info.display}) — "
                            "non-reentrant Lock: this self-deadlocks"))
            for node, callees, held, is_self in summary.call_sites:
                if not held:
                    continue
                for held_decl, _sp in held:
                    if held_decl.reentrant:
                        continue
                    eff = (self.eff_self_acquires if held_decl.owner
                           else self.eff_acquires)
                    if held_decl.owner and not is_self:
                        continue    # other instance: not provably the same
                    for callee in callees:
                        if held_decl.key not in eff.get(callee, set()):
                            continue
                        key = (held_decl.key, info.relpath, node.lineno)
                        if key in reported:
                            continue
                        reported.add(key)
                        findings.append(Finding(
                            rule_id, info.relpath, node.lineno,
                            f"call chain from {info.display} re-acquires "
                            f"non-reentrant "
                            f"{self._lock_name(held_decl.key)} via "
                            f"{self._display(callee)} while already "
                            "holding it — self-deadlock (use the _locked "
                            "convention or split the locked region)"))
        return findings

    def _chain(self, callee: str, site, eff) -> str:
        parts = [self._display(callee)]
        current = callee
        for _ in range(5):
            via = eff.get(current, {}).get(site)
            if via is None:
                break
            parts.append(self._display(via))
            current = via
        return " -> ".join(parts)


def build_lock_model(root: Path) -> LockModel:
    """The lock model for ``root`` — shared by the TH-LOCK rule, the
    witness comparator and the tests."""
    return LockModel(get_callgraph(root))


def compare_witness(dump_path: Path, root: Path) -> Tuple[bool, List[str]]:
    """Check a runtime lockwitness dump against the static model: observed
    edges must be a subset of the static order graph, every observed name
    must be a declared lock, and the run must have recorded no inversions.
    Returns ``(ok, report lines)`` — a failing line means either the model
    missed a real acquisition path (fix the analyzer, it is unsound) or
    the program deadlock-ordered differently than the code reads."""
    with open(dump_path) as fh:
        data = json.load(fh)
    model = build_lock_model(root)
    static_names = model.witness_names()
    static_edges = model.witness_edges()

    lines: List[str] = []
    ok = True

    observed_names = set(data.get("locks", {}))
    for a, b, _count in data.get("edges", []):
        observed_names.update((a, b))
    unknown = sorted(observed_names - static_names)
    for name in unknown:
        ok = False
        lines.append(f"witness: unknown lock {name!r}: observed at runtime "
                     "but never declared through the lockwitness factory in "
                     "scanned sources — the static model cannot see it")

    observed_edges = {(a, b) for a, b, _count in data.get("edges", [])}
    escaped = sorted(observed_edges - static_edges)
    for a, b in escaped:
        ok = False
        lines.append(f"witness: observed order {a} -> {b} is NOT in the "
                     "static graph — the analyzer missed an acquisition "
                     "path (unsound model; fix tools/analysis before "
                     "trusting TH-LOCK again)")

    for inv in data.get("inversions", []):
        ok = False
        cycle = " -> ".join(inv.get("cycle", []))
        lines.append(f"witness: runtime ABBA inversion {cycle} "
                     f"(thread {inv.get('thread')!r} held "
                     f"{inv.get('held')} while acquiring "
                     f"{inv.get('acquiring')!r})")

    lines.append(
        f"witness: {len(observed_edges)} observed edge(s) over "
        f"{len(observed_names)} lock(s) vs {len(static_edges)} static "
        f"edge(s) over {len(static_names)} declared name(s): "
        + ("observed ⊆ static, no inversions — the runtime agrees with "
           "the model" if ok else "MISMATCH"))
    return ok, lines


class LockOrderRule(ProjectRule):
    id = "TH-LOCK"
    title = "interprocedural lock-order / blocking / callback discipline"
    rationale = ("Deadlocks live in the composition of functions, not in "
                 "any one of them: lock-order cycles, blocking calls and "
                 "user callbacks reachable under a lock must be caught "
                 "across call chains before the fleet multiplies the "
                 "thread count.")
    scope = ("tensorhive_tpu/",)

    def check_project(self, root: Path) -> List[Finding]:
        model = build_lock_model(root)
        return model.findings(self.id)


register(LockOrderRule())
