"""Shared intra-module dataflow: the layer the flow-aware passes stand on.

The original passes (TH-C/TH-E/TH-B/TH-J) are per-statement pattern
matchers; the serving data plane's invariants (PRs 6-11) live one level up
— in how values FLOW: which callable a name is bound to (``_serving_step =
functools.partial(jax.jit, ...)(_step_body)``), which of a jit wrapper's
parameters are static vs traced vs donated, and where the wrapper is
actually called. This module computes that once per
:class:`~tools.analysis.engine.ModuleContext` (cached on the context, so
every flow-aware rule shares ONE pass, the same economy as the shared AST):

* **jit-wrapper recognition** — every way this repo spells a jitted
  function: ``@jax.jit`` / ``@jit(...)`` decorators,
  ``@functools.partial(jax.jit, static_argnames=...)`` decorators,
  ``name = jax.jit(fn, ...)`` and
  ``name = functools.partial(jax.jit, ...)(fn)`` assignments. Keyword
  values for ``static_argnames``/``static_argnums``/``donate_argnames``/
  ``donate_argnums`` are resolved through module-level constants
  (``static_argnames=_GENERATE_STATICS`` follows the assignment).
* **call-site indexing** — every ``ast.Call`` in the module keyed by the
  callee's terminal name (``f(...)`` -> ``f``, ``self._pool.release(...)``
  -> ``release``), so rules ask "where is this wrapper invoked" without
  re-walking.
* **module constants** — flat map of module-level ``NAME = <literal>``
  bindings, the conservative constant universe for "does a non-constant
  flow into a static position".

Everything here is lexical and module-flat, like the rest of the gate:
imports are not chased, attribute receivers are matched by source text.
That is the deliberate precision/recall trade the analyzer has made since
PR 2 — rules built on this layer keep the same contract.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

JIT_NAMES = {"jit", "pmap"}
PARTIAL_NAMES = {"partial"}

# -- the lock vocabulary shared by TH-C / TH-REF / TH-LOCK -------------------
#
# Three rule families reason about the same two conventions: "this attribute
# is a lock" (constructed from threading.Lock/RLock/Condition or the
# lockwitness named factory, whose functions deliberately reuse the same
# terminal names) and "a ``*_locked`` method asserts its caller already
# holds the instance lock". They MUST agree — a method the intraprocedural
# passes treat as guarded but the interprocedural pass treats as unguarded
# (or vice versa) silently splits the model. This is the one definition
# all three import (PR 17 satellite: the convention cannot drift again).

#: constructors that produce a lock object, by terminal callable name —
#: covers ``threading.Lock()`` and ``lockwitness.Lock("name")`` alike
LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: factories whose product a holder may re-acquire without deadlocking
#: (threading.Condition's default internal lock is an RLock)
REENTRANT_FACTORIES = {"RLock", "Condition"}

#: the caller-holds-the-lock naming contract (serving/engine.py et al.)
LOCKED_SUFFIX = "_locked"


def is_locked_name(name: str) -> bool:
    """True when ``name`` claims the caller-holds-the-lock convention."""
    return name.endswith(LOCKED_SUFFIX)


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` / ``self.X[...]`` -> ``X``; anything else -> None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def lock_factory_call(node: ast.AST) -> Optional[ast.Call]:
    """The ``Lock()``/``RLock()``/``Condition()`` construction inside an
    assigned value (handles ``lock or Lock()``), or None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            func = sub.func
            name = func.id if isinstance(func, ast.Name) else (
                func.attr if isinstance(func, ast.Attribute) else None)
            if name in LOCK_FACTORIES:
                return sub
    return None


def lock_factory_name(node: ast.AST) -> Optional[str]:
    """Which factory (``Lock``/``RLock``/``Condition``) constructs the
    value, or None when the expression builds no lock."""
    call = lock_factory_call(node)
    if call is None:
        return None
    func = call.func
    return func.id if isinstance(func, ast.Name) else func.attr


def is_lock_value(node: ast.AST) -> bool:
    return lock_factory_call(node) is not None


def class_lock_attrs(module, cls: ast.ClassDef) -> Dict[str, str]:
    """``{attr: factory}`` for every ``self.<attr> = ...Lock/RLock/
    Condition(...)`` whose nearest class is ``cls`` (nested classes are
    their own scope, matching TH-C)."""
    attrs: Dict[str, str] = {}
    for node in ast.walk(cls):
        if module.nearest_class(node) is not cls:
            continue
        if isinstance(node, ast.Assign):
            factory = lock_factory_name(node.value)
            if factory is None:
                continue
            for target in node.targets:
                attr = self_attr(target)
                if attr is not None:
                    attrs[attr] = factory
    return attrs


def _terminal_name(func: ast.AST) -> Optional[str]:
    """``f`` for ``f(...)``, ``attr`` for ``x.y.attr(...)``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def dotted_source(node: ast.AST) -> Optional[str]:
    """Best-effort dotted spelling of a Name/Attribute chain
    (``self._pool.page_table`` -> that exact string); None for anything
    with calls/subscripts in the chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_callable(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``pmap`` as a bare callable reference."""
    name = _terminal_name(node) if isinstance(node, (ast.Name, ast.Attribute)) \
        else None
    return name in JIT_NAMES


@dataclasses.dataclass
class JitWrapper:
    """One jitted callable the module defines, however it was spelled."""
    name: str                       # the bound / decorated name
    lineno: int
    target: Optional[str]           # the wrapped plain function's name
    static_argnames: Set[str]
    static_argnums: Set[int]
    donate_argnames: Set[str]
    donate_argnums: Set[int]

    def has_donation(self) -> bool:
        return bool(self.donate_argnames or self.donate_argnums)


class Dataflow:
    """The shared per-module flow facts. Build via ``Dataflow(module)``
    where ``module`` is a :class:`ModuleContext` (duck-typed: only
    ``tree``/``parents``/``ancestors`` are used)."""

    def __init__(self, module) -> None:
        self.module = module
        tree = module.tree
        #: module-flat function index (nested defs included, first wins)
        self.functions: Dict[str, ast.FunctionDef] = {}
        #: module-level NAME -> literal value (constants only)
        self.constants: Dict[str, object] = {}
        #: callee terminal name -> call nodes
        self.calls: Dict[str, List[ast.Call]] = {}
        self.jit_wrappers: Dict[str, JitWrapper] = {}
        if tree is None:
            return
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)
            elif isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name is not None:
                    self.calls.setdefault(name, []).append(node)
        for stmt in tree.body:        # module level only: constant universe
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                if isinstance(target, ast.Name):
                    try:
                        self.constants[target.id] = ast.literal_eval(
                            stmt.value)
                    except (ValueError, SyntaxError):
                        pass
        self._collect_wrappers(tree)

    # -- jit wrapper recognition ------------------------------------------
    def _collect_wrappers(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                wrapper = self._wrapper_from_decorators(node)
                if wrapper is not None:
                    self.jit_wrappers[wrapper.name] = wrapper
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if not isinstance(target, ast.Name):
                    continue
                wrapper = self._wrapper_from_expr(node.value, target.id,
                                                  node.lineno)
                if wrapper is not None:
                    self.jit_wrappers[wrapper.name] = wrapper

    def _wrapper_from_decorators(self, fn) -> Optional[JitWrapper]:
        for decorator in fn.decorator_list:
            info = self._jit_call_info(decorator)
            if info is not None:
                statics, static_nums, donated, donate_nums = info
                return JitWrapper(fn.name, fn.lineno, fn.name, statics,
                                  static_nums, donated, donate_nums)
        return None

    def _jit_call_info(self, node: ast.AST):
        """(static_argnames, static_argnums, donate_argnames,
        donate_argnums) when ``node`` is a jit application; None otherwise.
        Recognizes bare ``jax.jit``, ``jax.jit(**kw)`` and
        ``functools.partial(jax.jit, **kw)``."""
        if _is_jit_callable(node):
            return set(), set(), set(), set()
        if not isinstance(node, ast.Call):
            return None
        func = node.func
        if _is_jit_callable(func):
            return self._jit_kwargs(node)
        if (_terminal_name(func) in PARTIAL_NAMES and node.args
                and _is_jit_callable(node.args[0])):
            return self._jit_kwargs(node)
        return None

    def _jit_kwargs(self, call: ast.Call):
        statics: Set[str] = set()
        static_nums: Set[int] = set()
        donated: Set[str] = set()
        donate_nums: Set[int] = set()
        for kw in call.keywords:
            value = self._resolve_literal(kw.value)
            if value is None:
                continue
            names = {v for v in _as_tuple(value) if isinstance(v, str)}
            nums = {v for v in _as_tuple(value) if isinstance(v, int)}
            if kw.arg == "static_argnames":
                statics |= names
            elif kw.arg == "static_argnums":
                static_nums |= nums
            elif kw.arg == "donate_argnames":
                donated |= names
            elif kw.arg == "donate_argnums":
                donate_nums |= nums
        return statics, static_nums, donated, donate_nums

    def _wrapper_from_expr(self, value: ast.AST, bound: str,
                           lineno: int) -> Optional[JitWrapper]:
        """``bound = jax.jit(f, ...)`` or
        ``bound = functools.partial(jax.jit, ...)(f)``."""
        if not isinstance(value, ast.Call):
            return None
        # partial(jax.jit, **kw)(f): outer call's func is the partial call
        if isinstance(value.func, ast.Call):
            info = self._jit_call_info(value.func)
            if info is not None and value.args:
                target = value.args[0]
                if isinstance(target, ast.Name):
                    return JitWrapper(bound, lineno, target.id, *info)
            return None
        # jax.jit(f, **kw)
        if _is_jit_callable(value.func) and value.args:
            target = value.args[0]
            statics, static_nums, donated, donate_nums = self._jit_kwargs(
                value)
            target_name = (target.id if isinstance(target, ast.Name)
                           else None)
            return JitWrapper(bound, lineno, target_name, statics,
                              static_nums, donated, donate_nums)
        return None

    def _resolve_literal(self, node: ast.AST):
        """Literal value of an expression, following one module-constant
        hop (``static_argnames=_GENERATE_STATICS``)."""
        try:
            return ast.literal_eval(node)
        except (ValueError, SyntaxError):
            pass
        if isinstance(node, ast.Name):
            return self.constants.get(node.id)
        return None

    # -- queries -----------------------------------------------------------
    def call_sites(self, name: str) -> List[ast.Call]:
        return self.calls.get(name, [])

    def target_function(self, wrapper: JitWrapper) -> Optional[ast.AST]:
        if wrapper.target is None:
            return None
        return self.functions.get(wrapper.target)

    def target_params(self, wrapper: JitWrapper) -> List[str]:
        fn = self.target_function(wrapper)
        if fn is None:
            return []
        args = fn.args
        return [a.arg for a in [*args.posonlyargs, *args.args]]

    def static_params(self, wrapper: JitWrapper) -> Set[str]:
        params = self.target_params(wrapper)
        names = set(wrapper.static_argnames)
        for num in wrapper.static_argnums:
            if 0 <= num < len(params):
                names.add(params[num])
        return names

    def donated_params(self, wrapper: JitWrapper) -> Set[str]:
        params = self.target_params(wrapper)
        names = set(wrapper.donate_argnames)
        for num in wrapper.donate_argnums:
            if 0 <= num < len(params):
                names.add(params[num])
        return names

    def static_positions(self, wrapper: JitWrapper) -> Dict[int, str]:
        """positional index -> static param name at call sites."""
        params = self.target_params(wrapper)
        return {index: name for index, name in enumerate(params)
                if name in self.static_params(wrapper)}

    def donated_positions(self, wrapper: JitWrapper) -> Dict[int, str]:
        params = self.target_params(wrapper)
        return {index: name for index, name in enumerate(params)
                if name in self.donated_params(wrapper)}

    # -- scope helpers ------------------------------------------------------
    def enclosing_function(self, node: ast.AST):
        for ancestor in self.module.ancestors(node):
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return ancestor
        return None

    def enclosing_loops(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first For/While ancestors within the enclosing fn."""
        loops: List[ast.AST] = []
        for ancestor in self.module.ancestors(node):
            if isinstance(ancestor, (ast.For, ast.While)):
                loops.append(ancestor)
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        return loops

    @staticmethod
    def bound_in(scope: ast.AST) -> Set[str]:
        """Names assigned anywhere inside ``scope`` (loop targets, plain
        and augmented assignments, with/as, tuple unpacking)."""
        bound: Set[str] = set()

        def targets_of(node: ast.AST):
            if isinstance(node, ast.Name):
                bound.add(node.id)
            elif isinstance(node, (ast.Tuple, ast.List)):
                for element in node.elts:
                    targets_of(element)
            elif isinstance(node, ast.Starred):
                targets_of(node.value)

        for node in ast.walk(scope):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    targets_of(target)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets_of(node.target)
            elif isinstance(node, ast.For):
                targets_of(node.target)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        targets_of(item.optional_vars)
            elif isinstance(node, ast.NamedExpr):
                targets_of(node.target)
        return bound

    def _branch_index(self, structure: ast.AST,
                      node: ast.AST) -> Optional[int]:
        """Which branch list of an If/Try holds ``node`` (body=0,
        orelse=1, then handlers/finalbody); None when node is elsewhere
        (e.g. the test expression)."""
        chain = {id(node)} | {id(a) for a in self.module.ancestors(node)}
        if isinstance(structure, ast.If):
            branches: List[Sequence[ast.AST]] = [structure.body,
                                                 structure.orelse]
        elif isinstance(structure, ast.Try):
            branches = [structure.body, structure.orelse,
                        structure.finalbody]
            branches += [handler.body for handler in structure.handlers]
        else:
            return None
        for index, branch in enumerate(branches):
            if any(id(stmt) in chain for stmt in branch):
                return index
        return None

    def same_branch(self, anchor: ast.AST, other: ast.AST) -> bool:
        """False when ``other`` sits in the opposite arm of an ``if``
        (or try) that contains ``anchor`` — then-vs-else are mutually
        exclusive paths, so a lexically-later read there is never
        reachable after the anchor executes."""
        for ancestor in self.module.ancestors(anchor):
            if isinstance(ancestor, (ast.If, ast.Try)):
                mine = self._branch_index(ancestor, anchor)
                theirs = self._branch_index(ancestor, other)
                if mine is not None and theirs is not None and mine != theirs:
                    return False
        return True


def _as_tuple(value) -> Tuple:
    if isinstance(value, (tuple, list, set, frozenset)):
        return tuple(value)
    return (value,)


def call_argument(call: ast.Call, position: int,
                  name: str) -> Optional[ast.AST]:
    """The expression passed for parameter ``name`` (positional index
    ``position``) at this call site, or None when omitted."""
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    if position < len(call.args):
        arg = call.args[position]
        if isinstance(arg, ast.Starred):
            return None
        return arg
    return None
