"""thivelint: the repo's multi-pass static analyzer (see engine.py).

Run: ``python -m tools.analysis [paths...] [--format=json]``.
``python tools/lint.py`` remains a working alias for the same gate.
"""
from .engine import (  # noqa: F401
    Baseline,
    Finding,
    ModuleContext,
    Rule,
    all_rules,
    analyze_source,
    main,
    register,
    run,
    waiver_for,
)
