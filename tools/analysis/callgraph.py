"""Repo-wide call graph + lock declarations: the interprocedural layer.

The dataflow layer (PR 12) is deliberately module-flat; TH-LOCK needs the
one fact a flat view cannot give: *who calls whom across the repo while
holding what*. This module computes, once per root and cached like the
AST/dataflow contexts:

* **function index** — every module-level function and class method under
  the runtime package, keyed by a qualified name ``relpath::Class.method``
  / ``relpath::func``; ``@property`` getters are indexed too (a property
  read is a call the AST spells as an attribute load).
* **call resolution** — ``name()`` to the same-module (or unique
  cross-module) function; ``self.m()`` to the enclosing class's method,
  then lexical bases; ``ClassName(...)`` to ``__init__``; any other
  ``recv.m()`` to *every* repo class defining ``m`` (bounded by
  :data:`ATTR_FANOUT_CAP`). The last rule is a deliberate
  over-approximation: the witness comparator proves observed behavior is
  a subset of this model, so resolution must over- rather than
  under-approximate along real paths.
* **thread roots, not thread edges** — ``threading.Thread(target=f)`` /
  ``StoppableThread`` subclasses' ``do_run`` / ``@route`` handlers are
  recorded as entry points. A ``Thread(target=f)`` call must NOT be a
  call edge: ``f`` runs on a fresh thread with an empty held-set, so
  locks held at spawn time do not propagate into it.
* **lock declarations** — every ``self.X = ...Lock/RLock/Condition(...)``
  (class lock) and module-level ``NAME = ...Lock(...)``. Each lock gets a
  *witness name*: the string literal passed to the ``lockwitness`` named
  factory when present, else the ``Class.attr`` / ``pkg.mod.NAME``
  convention — the same name the runtime witness records, which is what
  makes the static and dynamic graphs comparable at all.
* **lock aliasing through constructors** — ``self._lock = lock`` fed from
  a constructor parameter (metrics children sharing their family's lock)
  resolves to the lock objects actually passed at the call sites, so an
  acquisition of ``Counter._lock`` is understood as an acquisition of
  ``MetricFamily._lock``.

Like every thivelint layer this is lexical: receivers are matched by
spelling, imports are not chased. The witness exists precisely to check
that this trade keeps telling the truth.
"""
from __future__ import annotations

import ast
import dataclasses
import functools
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .dataflow import (REENTRANT_FACTORIES, class_lock_attrs, is_locked_name,
                       lock_factory_call, lock_factory_name, self_attr)
from .engine import ModuleContext

#: generic ``recv.m()`` resolves to every repo class defining ``m`` unless
#: the name is so common the fan-out would wire unrelated subsystems
ATTR_FANOUT_CAP = 8

#: method names that are overwhelmingly stdlib container/str operations;
#: resolving them to repo classes that happen to share the name would
#: invent call edges out of every ``dict.get`` / ``list.append``
STDLIB_METHOD_NAMES = {
    "append", "appendleft", "add", "update", "extend", "insert", "remove",
    "pop", "popitem", "clear", "discard", "setdefault", "get", "items",
    "keys", "values", "copy", "split", "strip", "lstrip", "rstrip",
    "startswith", "endswith", "format", "encode", "decode", "lower",
    "upper", "replace", "count", "index", "sort", "reverse", "write",
    "read", "readline", "flush", "close", "join", "isoformat", "total",
    # sqlite cursor/connection API: resolving `conn.execute` to the repo's
    # own db Engine methods invents call chains from every SQL statement
    "execute", "executemany", "query",
}

PROPERTY_DECORATORS = {"property", "cached_property"}
ENTRYPOINT_KINDS = ("thread target", "service tick", "route handler")


def _terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


@dataclasses.dataclass(frozen=True)
class LockDecl:
    """One lock object the repo constructs, with its canonical identity."""

    key: str            # "relpath::Class.attr" / "relpath::NAME"
    witness_name: str   # the name the runtime witness would record
    relpath: str
    owner: str          # declaring class name, "" for module-level locks
    attr: str
    lineno: int
    factory: str        # Lock | RLock | Condition
    named: bool = False         # constructed via the lockwitness factory
    export_wait: bool = True    # False: export_wait=False at the site

    @property
    def reentrant(self) -> bool:
        return self.factory in REENTRANT_FACTORIES


@dataclasses.dataclass
class FunctionInfo:
    qname: str
    relpath: str
    cls: str            # "" for module-level functions
    name: str
    node: ast.AST
    module: ModuleContext
    is_property: bool = False
    entrypoint: Optional[str] = None    # one of ENTRYPOINT_KINDS

    @property
    def display(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


def _module_dotted(relpath: str) -> str:
    parts = relpath[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _witness_literal(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _kw_is_false(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name and isinstance(kw.value, ast.Constant)
               and kw.value.value is False for kw in call.keywords)


class CallGraph:
    """The interprocedural view of one repo root. Build via
    :func:`get_callgraph` (cached); ProjectRules treat instances as
    read-only."""

    def __init__(self, root: Path, contexts: List[ModuleContext]) -> None:
        self.root = root
        self.contexts = contexts
        self.functions: Dict[str, FunctionInfo] = {}
        self.locks: Dict[str, LockDecl] = {}
        #: (relpath, cls, attr) -> own LockDecl for class lock attributes
        self._class_locks: Dict[Tuple[str, str, str], LockDecl] = {}
        #: (relpath, name) -> LockDecl for module-level locks
        self._module_locks: Dict[Tuple[str, str], LockDecl] = {}
        #: class name -> [(relpath, ClassDef, ModuleContext)]
        self._classes: Dict[str, List[Tuple[str, ast.ClassDef,
                                            ModuleContext]]] = {}
        #: method name -> qnames across every class (incl. properties)
        self._methods: Dict[str, Set[str]] = {}
        #: property name -> qnames of @property getters
        self.properties: Dict[str, Set[str]] = {}
        #: function name -> qnames of module-level functions
        self._module_funcs: Dict[str, Set[str]] = {}
        #: (relpath, name) -> qname for same-module function lookup
        self._local_funcs: Dict[Tuple[str, str], str] = {}
        #: (relpath, cls) -> {method name -> qname}
        self._class_methods: Dict[Tuple[str, str], Dict[str, str]] = {}
        #: (relpath, cls) -> base class name spellings
        self._bases: Dict[Tuple[str, str], List[str]] = {}
        #: lock-attr aliases fed by a constructor parameter:
        #: (relpath, cls) -> {param name -> attr}
        self._lock_params: Dict[Tuple[str, str], Dict[str, str]] = {}
        #: resolved alias targets: (relpath, cls, attr) -> LockDecls passed
        self._alias_targets: Dict[Tuple[str, str, str], Set[LockDecl]] = {}
        self.edges: Dict[str, Set[str]] = {}

        for module in contexts:
            self._index_module(module)
        for module in contexts:
            self._collect_locks(module)
        for module in contexts:
            self._resolve_aliases(module)
        for info in list(self.functions.values()):
            callees = set()
            for call in ast.walk(info.node):
                if isinstance(call, ast.Call):
                    callees.update(self.resolve_call(info, call))
            self.edges[info.qname] = callees

    # -- indexing -----------------------------------------------------------
    def _index_module(self, module: ModuleContext) -> None:
        if module.tree is None:
            return
        relpath = module.relpath
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef) \
                    and module.nearest_class(node) is None:
                self._classes.setdefault(node.name, []).append(
                    (relpath, node, module))
                self._bases[(relpath, node.name)] = [
                    b.id if isinstance(b, ast.Name) else b.attr
                    for b in node.bases
                    if isinstance(b, (ast.Name, ast.Attribute))]
                self._index_class(module, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and module.nearest_class(node) is None \
                    and self._is_top_level(module, node):
                qname = f"{relpath}::{node.name}"
                self.functions[qname] = FunctionInfo(
                    qname, relpath, "", node.name, node, module)
                self._module_funcs.setdefault(node.name, set()).add(qname)
                self._local_funcs[(relpath, node.name)] = qname

    def _is_top_level(self, module: ModuleContext, node: ast.AST) -> bool:
        for ancestor in module.ancestors(node):
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return True

    def _index_class(self, module: ModuleContext, cls: ast.ClassDef) -> None:
        relpath = module.relpath
        methods: Dict[str, str] = {}
        for node in ast.walk(cls):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if module.nearest_class(node) is not cls:
                continue
            qname = f"{relpath}::{cls.name}.{node.name}"
            is_prop = any(
                _terminal(d.func if isinstance(d, ast.Call) else d)
                in PROPERTY_DECORATORS for d in node.decorator_list)
            entry = None
            if node.name == "do_run":
                entry = "service tick"
            elif any(_terminal(d.func if isinstance(d, ast.Call) else d)
                     == "route" for d in node.decorator_list):
                entry = "route handler"
            info = FunctionInfo(qname, relpath, cls.name, node.name, node,
                                module, is_property=is_prop, entrypoint=entry)
            self.functions[qname] = info
            methods[node.name] = qname
            self._methods.setdefault(node.name, set()).add(qname)
            if is_prop:
                self.properties.setdefault(node.name, set()).add(qname)
        self._class_methods[(relpath, cls.name)] = methods

    # -- lock declarations --------------------------------------------------
    def _collect_locks(self, module: ModuleContext) -> None:
        if module.tree is None:
            return
        relpath = module.relpath
        for stmt in module.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            factory = lock_factory_name(stmt.value)
            if factory is None:
                continue
            call = lock_factory_call(stmt.value)
            for target in stmt.targets:
                if not isinstance(target, ast.Name):
                    continue
                literal = _witness_literal(call)
                witness = literal or \
                    f"{_module_dotted(relpath)}.{target.id}"
                decl = LockDecl(f"{relpath}::{target.id}", witness, relpath,
                                "", target.id, stmt.lineno, factory,
                                named=literal is not None,
                                export_wait=not _kw_is_false(
                                    call, "export_wait"))
                self.locks[decl.key] = decl
                self._module_locks[(relpath, target.id)] = decl
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if module.nearest_class(node) is not None:
                continue
            self._collect_class_locks(module, node)

    def _collect_class_locks(self, module: ModuleContext,
                             cls: ast.ClassDef) -> None:
        relpath = module.relpath
        ctor_params = self._ctor_params(cls)
        for node in ast.walk(cls):
            if module.nearest_class(node) is not cls:
                continue
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                attr = self_attr(target)
                if attr is None:
                    continue
                factory = lock_factory_name(node.value)
                if factory is not None:
                    call = lock_factory_call(node.value)
                    literal = _witness_literal(call)
                    witness = literal or f"{cls.name}.{attr}"
                    decl = LockDecl(f"{relpath}::{cls.name}.{attr}", witness,
                                    relpath, cls.name, attr, node.lineno,
                                    factory, named=literal is not None,
                                    export_wait=not _kw_is_false(
                                        call, "export_wait"))
                    self.locks[decl.key] = decl
                    self._class_locks[(relpath, cls.name, attr)] = decl
                # `self._lock = lock` / `self._lock = lock or Lock()`:
                # the attr may also alias a lock passed by the constructor
                for name_node in ast.walk(node.value):
                    if isinstance(name_node, ast.Name) \
                            and name_node.id in ctor_params:
                        self._lock_params.setdefault(
                            (relpath, cls.name), {})[name_node.id] = attr

    @staticmethod
    def _ctor_params(cls: ast.ClassDef) -> List[str]:
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name == "__init__":
                args = stmt.args
                names = [a.arg for a in args.posonlyargs + args.args
                         + args.kwonlyargs]
                return [n for n in names if n != "self"]
        return []

    def _resolve_aliases(self, module: ModuleContext) -> None:
        """Find constructor calls that pass a known lock into a class whose
        lock attr aliases a constructor parameter (metrics children)."""
        if module.tree is None:
            return
        for call in ast.walk(module.tree):
            if not isinstance(call, ast.Call):
                continue
            name = _terminal(call.func)
            if name not in self._classes:
                continue
            for relpath, cls, _cls_module in self._classes[name]:
                params = self._lock_params.get((relpath, name))
                if not params:
                    continue
                bound = self._bind_ctor_args(cls, call)
                for param, attr in params.items():
                    expr = bound.get(param)
                    if expr is None:
                        continue
                    decl = self._lock_expr_decl(module, expr)
                    if decl is not None:
                        self._alias_targets.setdefault(
                            (relpath, name, attr), set()).add(decl)

    def _bind_ctor_args(self, cls: ast.ClassDef,
                        call: ast.Call) -> Dict[str, ast.AST]:
        params = self._ctor_params(cls)
        bound: Dict[str, ast.AST] = {}
        for i, arg in enumerate(call.args):
            if i < len(params):
                bound[params[i]] = arg
        for kw in call.keywords:
            if kw.arg is not None:
                bound[kw.arg] = kw.value
        return bound

    def _lock_expr_decl(self, module: ModuleContext,
                        expr: ast.AST) -> Optional[LockDecl]:
        """The LockDecl a constructor-argument expression denotes, when it
        is spelled ``self.X`` (in a class owning lock X) or a module-level
        lock name."""
        attr = self_attr(expr)
        if attr is not None:
            cls = module.nearest_class(expr)
            if cls is not None:
                return self._class_locks.get(
                    (module.relpath, cls.name, attr))
            return None
        if isinstance(expr, ast.Name):
            return self._module_locks.get((module.relpath, expr.id))
        return None

    # -- lock lookups used by TH-LOCK ---------------------------------------
    def class_lock_decls(self, module: ModuleContext,
                         cls: ast.ClassDef) -> Dict[str, LockDecl]:
        """attr -> own LockDecl for every lock attribute of ``cls``."""
        decls = {}
        for attr in class_lock_attrs(module, cls):
            decl = self._class_locks.get((module.relpath, cls.name, attr))
            if decl is not None:
                decls[attr] = decl
        return decls

    def acquire_targets(self, relpath: str, cls: str,
                        attr: str) -> Set[LockDecl]:
        """Every lock object an acquisition of ``self.<attr>`` in class
        ``cls`` may actually lock: its own declaration plus any lock
        aliased into it through a constructor parameter."""
        targets: Set[LockDecl] = set()
        own = self._class_locks.get((relpath, cls, attr))
        if own is not None:
            targets.add(own)
        targets.update(self._alias_targets.get((relpath, cls, attr), set()))
        return targets

    def module_lock(self, relpath: str, name: str) -> Optional[LockDecl]:
        return self._module_locks.get((relpath, name))

    def is_lock_attr(self, relpath: str, cls: str, attr: str) -> bool:
        return (relpath, cls, attr) in self._class_locks \
            or (relpath, cls, attr) in self._alias_targets

    # -- call resolution ----------------------------------------------------
    def resolve_call(self, info: FunctionInfo,
                     call: ast.Call) -> Set[str]:
        """Qnames ``call`` (inside ``info``) may invoke on the SAME thread.
        ``Thread(target=...)`` resolves to nothing — the target is a root,
        recorded via :meth:`thread_target`."""
        func = call.func
        if self.thread_target(info, call) is not None:
            return set()
        if isinstance(func, ast.Name):
            return self._resolve_name(info, func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self" \
                    and info.cls:
                resolved = self._resolve_self_method(info.relpath, info.cls,
                                                     func.attr)
                if resolved:
                    return resolved
            return self._resolve_method(func.attr)
        return set()

    def _resolve_name(self, info: FunctionInfo, name: str) -> Set[str]:
        local = self._local_funcs.get((info.relpath, name))
        if local is not None:
            return {local}
        if name in self._classes:
            ctors = set()
            for relpath, cls, _m in self._classes[name]:
                ctor = self._class_methods.get((relpath, cls.name),
                                               {}).get("__init__")
                if ctor is not None:
                    ctors.add(ctor)
            return ctors
        funcs = self._module_funcs.get(name, set())
        if len(funcs) == 1:
            return set(funcs)
        return set()

    def _resolve_self_method(self, relpath: str, cls: str,
                             method: str) -> Set[str]:
        qname = self._class_methods.get((relpath, cls), {}).get(method)
        if qname is not None:
            return {qname}
        for base in self._bases.get((relpath, cls), []):
            for base_rel, base_cls, _m in self._classes.get(base, []):
                found = self._resolve_self_method(base_rel, base_cls.name,
                                                  method)
                if found:
                    return found
        return set()

    def _resolve_method(self, method: str) -> Set[str]:
        if method in STDLIB_METHOD_NAMES:
            return set()
        candidates = set(self._methods.get(method, set()))
        funcs = self._module_funcs.get(method, set())
        if len(funcs) == 1:
            candidates.update(funcs)
        if 0 < len(candidates) <= ATTR_FANOUT_CAP:
            return candidates
        return set()

    def resolve_property_load(self, attr: str) -> Set[str]:
        """Qnames an attribute LOAD may invoke when ``attr`` names a
        ``@property`` getter somewhere in the repo (``child.value`` takes
        the family lock without a single ``ast.Call`` in sight)."""
        props = self.properties.get(attr, set())
        if len(props) <= ATTR_FANOUT_CAP:
            return set(props)
        return set()

    def thread_target(self, info: FunctionInfo,
                      call: ast.Call) -> Optional[str]:
        """The qname spawned by a ``Thread(target=...)`` call, else None."""
        if _terminal(call.func) not in {"Thread", "StoppableThread"}:
            return None
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            value = kw.value
            if isinstance(value, ast.Name):
                resolved = self._resolve_name(info, value.id)
            elif isinstance(value, ast.Attribute) \
                    and isinstance(value.value, ast.Name) \
                    and value.value.id == "self" and info.cls:
                resolved = self._resolve_self_method(info.relpath, info.cls,
                                                     value.attr)
            else:
                resolved = set()
            for qname in resolved:
                self.functions[qname].entrypoint = "thread target"
            return next(iter(resolved), "<unresolved>")
        return "<unresolved>"

    def convention_locks(self, info: FunctionInfo) -> Set[LockDecl]:
        """Locks a ``*_locked`` method holds by contract: every lock its
        class declares (the caller-holds-the-lock convention, shared with
        TH-C/TH-REF via dataflow.is_locked_name)."""
        if not info.cls or not is_locked_name(info.name):
            return set()
        held: Set[LockDecl] = set()
        for (relpath, cls, attr), decl in self._class_locks.items():
            if relpath == info.relpath and cls == info.cls:
                held.add(decl)
        return held


# -- cached construction ----------------------------------------------------
SKIP_DIRS = {"tests", "docs", "examples", ".git", "__pycache__", "build",
             "node_modules", ".claude"}


def _walk_sources(root: Path) -> List[Path]:
    package = root / "tensorhive_tpu"
    base = package if package.is_dir() else root
    files = []
    for path in sorted(base.rglob("*.py")):
        rel = path.relative_to(root)
        if any(part in SKIP_DIRS for part in rel.parts):
            continue
        files.append(path)
    return files


def _fingerprint(root: Path) -> Tuple[Tuple[str, float, int], ...]:
    out = []
    for path in _walk_sources(root):
        try:
            stat = path.stat()
        except OSError:
            continue
        out.append((path.as_posix(), stat.st_mtime, stat.st_size))
    return tuple(out)


@functools.lru_cache(maxsize=4)
def _build(root_str: str,
           fingerprint: Tuple[Tuple[str, float, int], ...]) -> CallGraph:
    root = Path(root_str)
    contexts = []
    for path in _walk_sources(root):
        try:
            relpath = path.relative_to(root).as_posix()
        except ValueError:
            relpath = path.as_posix()
        try:
            contexts.append(ModuleContext(path.read_text(), relpath,
                                          path=path))
        except OSError:
            continue
    return CallGraph(root, contexts)


def get_callgraph(root: Path) -> CallGraph:
    """The (cached) call graph for ``root`` — same economy as the shared
    AST: every ProjectRule in a run sees one build."""
    root = root.resolve()
    return _build(str(root), _fingerprint(root))
