"""Smoke-test int8 KV pages end to end (``make quant-smoke``;
docs/SERVING.md "Quantized KV pages").

Boots the real daemon surface — WSGI app over a real socket, a live
GenerationService pump, in-memory DB — around a ``kv_quant=on`` engine,
then proves the quantized plane's operational contract over HTTP:

1. stream one authenticated ``POST /api/generate`` request through the
   quant-on engine and record its tokens; ``/api/generate/stats`` must
   report ``kvQuant=on`` with the int8 ``kvBytesPerToken``;
2. the ``/api/metrics`` scrape must export the byte-level pool gauges
   ``tpuhive_generate_kv_bytes_capacity`` / ``_used`` (``_capacity``, not
   ``_total`` — the PR 12 TH-X naming guidance for gauges);
3. ZERO post-warmup recompiles across page assignment AND scale updates
   (step + prefill executables fingerprint-stable while the request runs);
4. swap in a ``kv_quant="off"`` engine built from the SAME params and
   stream the SAME prompt: the greedy token match rate must be >=
   ``MATCH_RATE_GATE`` (both streams are deterministic, so the rate is a
   reproducible numerics statement, not a flaky sample);
5. at EQUAL HBM BYTES — an f32 pool vs an int8 pool holding the identical
   byte budget — the quantized pool must admit >= ``CONCURRENCY_GATE``x
   the concurrent sequences (in-process engines, the serving_smoke
   scenario-5 shape).

Engines run the f32 tiny config (like the unit suite): the match-rate gate
is a numerics statement and must not be confounded with bf16
accumulation-order flips (the PR 3 caveat).

Exit 0 = healthy.
"""
from __future__ import annotations

import dataclasses
import json
import os
import sys
import urllib.error
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("TPUHIVE_PYTEST", "1")          # DB goes in-memory

#: 24 tokens: long enough that the tiny random-init model's argmax
#: margins are not one-ULP ties on every step (an 8-token probe measured
#: 0.25 — near-uniform logits decorrelate after the first flipped tie,
#: which says "untrained model", not "broken quantizer"; the perplexity
#: gate in bench is the quality statement)
PROMPT = list(range(3, 27))
NEW_TOKENS = 12
#: deterministic greedy agreement between the int8 and f32 engines on the
#: probe prompt (measured 1.0 on this seed; the gate leaves margin for
#: jax version drift without ever accepting a broken quantizer)
MATCH_RATE_GATE = 0.75
#: int8 pages must admit at least this multiple of the f32 pool's
#: concurrent sequences at the same HBM byte budget (measured 3.5x at f32
#: cells; the ISSUE gate is 1.8x — the bf16-baseline doubling story)
CONCURRENCY_GATE = 1.8

PROBLEMS = []


def check(ok: bool, what: str) -> None:
    status = "ok" if ok else "FAIL"
    print(f"quant-smoke: {status}: {what}")
    if not ok:
        PROBLEMS.append(what)


def request(url: str, body=None, headers=None, method=None):
    """(status, text, headers) over real HTTP; >=400 is a result."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def stream_tokens(base: str, auth: dict):
    status, body, headers = request(f"{base}/generate", body={
        "promptTokens": PROMPT, "maxNewTokens": NEW_TOKENS,
        "temperature": 0}, headers=auth)
    check(status == 200, f"POST /generate streamed (got {status})")
    lines = [json.loads(line) for line in body.strip().splitlines()]
    done = lines[-1]
    check(done.get("outcome") == "completed",
          f"stream completed (got {done})")
    return done.get("tokens")


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tensorhive_tpu.config import Config, set_config

    config = Config(config_dir=Path("/tmp/tpuhive-quant-smoke"))
    config.api.secret_key = "quant-smoke-secret"
    config.generation.enabled = True
    config.generation.interval_s = 0.01
    set_config(config)

    from tensorhive_tpu.db.engine import Engine, set_engine as set_db
    from tensorhive_tpu.db.migrations import ensure_schema

    engine_db = Engine(":memory:")
    ensure_schema(engine_db)
    set_db(engine_db)

    from tensorhive_tpu.db.models import User

    admin = User(username="smoke-admin", email="smoke@example.com",
                 password="SuperSecret42").save()
    admin.add_role("user")
    admin.add_role("admin")

    from tensorhive_tpu.core.services.generation import GenerationService
    from tensorhive_tpu.models.transformer import PRESETS, TransformerLM
    from tensorhive_tpu.serving.engine import SlotEngine

    f32_tiny = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32,
                                   use_flash=False, remat=False,
                                   max_seq_len=128)
    params = TransformerLM.init(jax.random.PRNGKey(0), f32_tiny)

    def build(kv_quant: str, **kwargs) -> SlotEngine:
        engine = SlotEngine(params, f32_tiny, slots=2, max_len=96,
                            queue_depth=4, kv_quant=kv_quant, **kwargs)
        engine.warmup(prompt_lens=(len(PROMPT),))
        return engine

    quant_engine = build("on")
    check(quant_engine.stats()["kvQuant"] == "on",
          "kv_quant engine resolved on")
    step_execs = quant_engine.step_executable._cache_size()
    prefill_execs = quant_engine.prefill_executable._cache_size()

    generation = GenerationService(config=config, engine=quant_engine)
    generation.start()

    from tensorhive_tpu.api.server import APIServer

    server = APIServer()
    server.config.api.url_hostname = "127.0.0.1"
    server.config.api.url_port = 0                     # ephemeral
    port = server.start()
    base = f"http://127.0.0.1:{port}/api"
    off_service = None
    try:
        status, body, _ = request(f"{base}/user/login", body={
            "username": "smoke-admin", "password": "SuperSecret42"})
        check(status == 200, f"admin login over HTTP (got {status})")
        auth = {"Authorization": "Bearer " + json.loads(body)["accessToken"]}

        # -- 1: quant-on stream + stats report the int8 plane --------------
        quant_tokens = stream_tokens(base, auth)
        check(isinstance(quant_tokens, list)
              and len(quant_tokens) == NEW_TOKENS,
              f"quant-on stream emitted {NEW_TOKENS} tokens")
        status, body, _ = request(f"{base}/generate/stats", headers=auth)
        check(status == 200, f"GET /generate/stats (got {status})")
        stats = json.loads(body)
        check(stats["kvQuant"] == "on", "stats report kvQuant=on")
        check((stats["kvBytesPerToken"] or 1e9) < 512,
              f"int8 kvBytesPerToken ({stats['kvBytesPerToken']}) below "
              "the f32 cost")

        # -- 2: byte-level pool gauges in the scrape -----------------------
        status, scrape, _ = request(f"{base}/metrics")
        check(status == 200, f"GET /metrics (got {status})")
        check("tpuhive_generate_kv_bytes_capacity" in scrape,
              "kv_bytes_capacity gauge in the exposition")
        check("tpuhive_generate_kv_bytes_used" in scrape,
              "kv_bytes_used gauge in the exposition")

        # -- 3: zero post-warmup recompiles across scale updates -----------
        check(quant_engine.step_executable._cache_size() == step_execs
              and quant_engine.prefill_executable._cache_size()
              == prefill_execs,
              "zero new executables while the quantized request ran")

        # -- 4: greedy match rate vs the f32 engine ------------------------
        generation.shutdown()
        generation.join(timeout=5)
        off_engine = build("off")
        off_service = GenerationService(config=config, engine=off_engine)
        off_service.start()
        off_tokens = stream_tokens(base, auth)
        matches = sum(a == b for a, b in zip(quant_tokens, off_tokens))
        rate = matches / max(1, len(off_tokens))
        check(rate >= MATCH_RATE_GATE,
              f"greedy match rate {rate:.3f} >= {MATCH_RATE_GATE} "
              f"({quant_tokens} vs {off_tokens})")
    finally:
        server.stop()
        generation.shutdown()
        generation.join(timeout=5)
        if off_service is not None:
            off_service.shutdown()
            off_service.join(timeout=5)

    # -- 5: >= 1.8x concurrent admitted sequences at EQUAL HBM bytes -------
    from tensorhive_tpu.ops import kv_quant as kvq

    page_size = 16
    probe_pages = -(-(len(PROMPT) + NEW_TOKENS) // page_size)
    f32_pages = 2 * probe_pages
    layer_f32 = kvq.page_bytes(page_size, f32_tiny.kv_heads,
                               f32_tiny.d_head, 4)
    layer_q = kvq.quant_page_bytes(page_size, f32_tiny.kv_heads,
                                   f32_tiny.d_head)
    quant_pages = f32_pages * layer_f32 // layer_q

    def peak_concurrency(kv_quant: str, kv_pages: int) -> int:
        pool = SlotEngine(params, f32_tiny, slots=8, max_len=96,
                          queue_depth=8, page_size=page_size,
                          kv_pages=kv_pages, kv_quant=kv_quant,
                          prefix_cache="off")
        pool.warmup(prompt_lens=(len(PROMPT),))
        handles = [pool.submit(PROMPT, max_new_tokens=NEW_TOKENS)
                   for _ in range(8)]
        peak = 0
        while pool.has_work():
            pool.step()
            peak = max(peak, pool.stats()["slotsBusy"])
        assert all(handle.done for handle in handles)
        return peak

    busy_f32 = peak_concurrency("off", f32_pages)
    busy_q = peak_concurrency("on", quant_pages)
    ratio = busy_q / max(1, busy_f32)
    check(ratio >= CONCURRENCY_GATE,
          f"int8 admits {busy_q} vs f32 {busy_f32} concurrent at equal "
          f"HBM ({f32_pages} f32 pages == {quant_pages} int8 pages): "
          f"{ratio:.2f}x >= {CONCURRENCY_GATE}x")

    if PROBLEMS:
        print(f"quant-smoke: {len(PROBLEMS)} problem(s)", file=sys.stderr)
        return 1
    print("quant-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
