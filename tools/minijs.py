"""A small, strict interpreter for the ES subset the in-repo web UI uses.

Why this exists: the UI is ~1.8k LoC of hand-rolled JS (calendar date math,
month-view anchoring, the job template dialog) and the reference's Vue app
was exercised by a browser; this image ships NO JavaScript engine (no node,
no quickjs, no embeddable libv8 — verified), so the only way to *execute*
the UI in CI is to interpret it. This module does exactly that: a
tokenizer, a recursive-descent parser and a tree-walking evaluator for the
constructs the UI actually uses, with JS semantics where they matter
(number formatting, truthiness, Date month-overflow normalization, ==/===,
template literals, sync-resolved promises for the UI's await/then chains).

Deliberately STRICT: any construct outside the subset raises JSError with
a position instead of approximating — a misleading pass would be worse
than no test. The DOM/browser environment lives in tools/minidom.py; the
UI tests (tests/unit/test_ui_dom.py) wire fetch to the real WSGI app.

This is a dev/test tool like tools/lint.py, not part of the served
product.
"""
from __future__ import annotations

import json
import math
import re as _re
from datetime import datetime, timedelta, timezone
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Interpreter", "JSError", "JSException", "UNDEFINED", "JSObject",
           "JSArray", "JSFunction", "JSDate", "js_truthy", "js_str"]


class JSError(Exception):
    """Tokenizer/parser/interpreter-level failure (unsupported construct,
    syntax error, internal limit). NOT a JS-level thrown value."""


class JSException(Exception):
    """A JS-level `throw`; .value is the thrown JS value."""

    def __init__(self, value):
        super().__init__(js_str(value))
        self.value = value


class _Undefined:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


UNDEFINED = _Undefined()

NULL = None


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

KEYWORDS = {
    "var", "let", "const", "function", "return", "if", "else", "for", "of",
    "in", "while", "do", "break", "continue", "new", "typeof", "delete",
    "try", "catch", "finally", "throw", "true", "false", "null", "undefined",
    "async", "await", "instanceof", "this", "switch", "case", "default",
    "class", "yield", "void",
}

PUNCT = sorted([
    "===", "!==", "**=", "...", "=>", "==", "!=", "<=", ">=", "&&", "||",
    "??", "++", "--", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=",
    "&", "|", "^", "~", "<<", ">>", ">>>",
    "{", "}", "(", ")", "[", "]", ";", ",", "<", ">", "+", "-", "*", "/",
    "%", "=", "!", "?", ":", ".",
], key=len, reverse=True)


class Token:
    __slots__ = ("kind", "value", "pos", "line")

    def __init__(self, kind, value, pos, line):
        self.kind = kind          # num str template regex ident keyword punct eof
        self.value = value
        self.pos = pos
        self.line = line

    def __repr__(self):
        return f"Token({self.kind},{self.value!r},l{self.line})"


def tokenize(source: str, filename: str = "<js>") -> List[Token]:
    tokens: List[Token] = []
    i, n, line = 0, len(source), 1

    def error(msg):
        raise JSError(f"{filename}:{line}: {msg}")

    def prev_significant():
        return tokens[-1] if tokens else None

    while i < n:
        ch = source[i]
        if ch == "\n":
            line += 1
            i += 1
            continue
        if ch in " \t\r":
            i += 1
            continue
        if source.startswith("//", i):
            j = source.find("\n", i)
            i = n if j < 0 else j
            continue
        if source.startswith("/*", i):
            j = source.find("*/", i)
            if j < 0:
                error("unterminated block comment")
            line += source.count("\n", i, j)
            i = j + 2
            continue
        if ch.isdigit() or (ch == "." and i + 1 < n and source[i + 1].isdigit()):
            m = _re.match(r"0[xX][0-9a-fA-F]+|\d*\.?\d+(?:[eE][+-]?\d+)?", source[i:])
            text = m.group(0)
            value = float(int(text, 16)) if text[:2].lower() == "0x" else float(text)
            tokens.append(Token("num", value, i, line))
            i += len(text)
            continue
        if ch in "'\"":
            j, buf = i + 1, []
            while j < n and source[j] != ch:
                if source[j] == "\\":
                    buf.append(_unescape(source[j + 1], error))
                    j += 2
                else:
                    if source[j] == "\n":
                        error("unterminated string")
                    buf.append(source[j])
                    j += 1
            if j >= n:
                error("unterminated string")
            tokens.append(Token("str", "".join(buf), i, line))
            i = j + 1
            continue
        if ch == "`":
            parts, exprs, j, buf = [], [], i + 1, []
            while True:
                if j >= n:
                    error("unterminated template literal")
                c = source[j]
                if c == "`":
                    parts.append("".join(buf))
                    j += 1
                    break
                if c == "\\":
                    buf.append(_unescape(source[j + 1], error))
                    j += 2
                    continue
                if c == "$" and j + 1 < n and source[j + 1] == "{":
                    parts.append("".join(buf))
                    buf = []
                    depth, k = 1, j + 2
                    while k < n and depth:
                        if source[k] == "`":       # nested template: skip it
                            k = _skip_template(source, k, error)
                            continue
                        if source[k] == "{":
                            depth += 1
                        elif source[k] == "}":
                            depth -= 1
                            if not depth:
                                break
                        elif source[k] in "'\"":
                            k = _skip_string(source, k, error)
                            continue
                        k += 1
                    if depth:
                        error("unterminated ${ in template")
                    exprs.append(source[j + 2:k])
                    j = k + 1
                    continue
                if c == "\n":
                    line += 1
                buf.append(c)
                j += 1
            tokens.append(Token("template", (parts, exprs), i, line))
            i = j
            continue
        if ch.isalpha() or ch in "_$":
            m = _re.match(r"[A-Za-z_$][A-Za-z0-9_$]*", source[i:])
            word = m.group(0)
            kind = "keyword" if word in KEYWORDS else "ident"
            tokens.append(Token(kind, word, i, line))
            i += len(word)
            continue
        if ch == "/":
            prev = prev_significant()
            is_regex = prev is None or (
                prev.kind == "punct" and prev.value not in (")", "]")
            ) or (prev.kind == "keyword" and prev.value not in
                  ("this", "true", "false", "null", "undefined"))
            if is_regex:
                j, in_class = i + 1, False
                while j < n:
                    c = source[j]
                    if c == "\\":
                        j += 2
                        continue
                    if c == "[":
                        in_class = True
                    elif c == "]":
                        in_class = False
                    elif c == "/" and not in_class:
                        break
                    elif c == "\n":
                        error("unterminated regex literal")
                    j += 1
                if j >= n:
                    error("unterminated regex literal")
                pattern = source[i + 1:j]
                m = _re.match(r"[a-z]*", source[j + 1:])
                flags = m.group(0)
                tokens.append(Token("regex", (pattern, flags), i, line))
                i = j + 1 + len(flags)
                continue
        for punct in PUNCT:
            if source.startswith(punct, i):
                tokens.append(Token("punct", punct, i, line))
                i += len(punct)
                break
        else:
            error(f"unexpected character {ch!r}")
    tokens.append(Token("eof", None, i, line))
    return tokens


def _unescape(ch, error):
    table = {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
             "0": "\0", "\n": ""}
    return table.get(ch, ch)


def _skip_string(source, i, error):
    quote, j = source[i], i + 1
    while j < len(source) and source[j] != quote:
        j += 2 if source[j] == "\\" else 1
    if j >= len(source):
        error("unterminated string in template expression")
    return j + 1


def _skip_template(source, i, error):
    j = i + 1
    while j < len(source):
        c = source[j]
        if c == "\\":
            j += 2
            continue
        if c == "`":
            return j + 1
        if c == "$" and j + 1 < len(source) and source[j + 1] == "{":
            depth, j = 1, j + 2
            while j < len(source) and depth:
                if source[j] == "`":
                    j = _skip_template(source, j, error)
                    continue
                if source[j] == "{":
                    depth += 1
                elif source[j] == "}":
                    depth -= 1
                elif source[j] in "'\"":
                    j = _skip_string(source, j, error)
                    continue
                j += 1
            continue
        j += 1
    error("unterminated nested template literal")


# ---------------------------------------------------------------------------
# parser — AST nodes are ("kind", ...) tuples
# ---------------------------------------------------------------------------

ASSIGN_OPS = {"=", "+=", "-=", "*=", "/=", "%="}


class Parser:
    def __init__(self, tokens: List[Token], filename: str = "<js>"):
        self.tokens = tokens
        self.pos = 0
        self.filename = filename

    # -- helpers ------------------------------------------------------------
    def peek(self, offset=0) -> Token:
        return self.tokens[min(self.pos + offset, len(self.tokens) - 1)]

    def next(self) -> Token:
        token = self.peek()
        self.pos += 1
        return token

    def at(self, kind, value=None) -> bool:
        token = self.peek()
        return token.kind == kind and (value is None or token.value == value)

    def eat(self, kind, value=None) -> Optional[Token]:
        if self.at(kind, value):
            return self.next()
        return None

    def expect(self, kind, value=None) -> Token:
        token = self.peek()
        if not self.at(kind, value):
            self.error(f"expected {value or kind}, got {token.kind} {token.value!r}")
        return self.next()

    def error(self, msg):
        token = self.peek()
        raise JSError(f"{self.filename}:{token.line}: parse error: {msg}")

    # -- program ------------------------------------------------------------
    def parse_program(self):
        body = []
        while not self.at("eof"):
            body.append(self.statement())
        return ("program", body)

    # -- statements ---------------------------------------------------------
    def statement(self):
        token = self.peek()
        if token.kind == "punct" and token.value == ";":
            self.next()
            return ("empty",)
        if token.kind == "punct" and token.value == "{":
            return self.block()
        if token.kind == "keyword":
            word = token.value
            if word in ("var", "let", "const"):
                decl = self.var_decl()
                self.eat("punct", ";")
                return decl
            if word == "function":
                return self.function_decl(is_async=False)
            if word == "async" and self.peek(1).kind == "keyword" \
                    and self.peek(1).value == "function":
                self.next()
                return self.function_decl(is_async=True)
            if word == "if":
                return self.if_stmt()
            if word == "for":
                return self.for_stmt()
            if word == "while":
                self.next()
                self.expect("punct", "(")
                test = self.expression()
                self.expect("punct", ")")
                return ("while", test, self.statement())
            if word == "return":
                self.next()
                if self.at("punct", ";") or self.at("punct", "}") or self.at("eof"):
                    self.eat("punct", ";")
                    return ("return", None)
                value = self.expression()
                self.eat("punct", ";")
                return ("return", value)
            if word == "throw":
                self.next()
                value = self.expression()
                self.eat("punct", ";")
                return ("throw", value)
            if word == "break":
                self.next()
                self.eat("punct", ";")
                return ("break",)
            if word == "continue":
                self.next()
                self.eat("punct", ";")
                return ("continue",)
            if word == "try":
                return self.try_stmt()
            if word in ("class", "switch", "do", "yield"):
                self.error(f"unsupported construct '{word}' — extend tools/minijs.py")
        expr = self.expression()
        self.eat("punct", ";")
        return ("exprstmt", expr)

    def block(self):
        self.expect("punct", "{")
        body = []
        while not self.at("punct", "}"):
            body.append(self.statement())
        self.expect("punct", "}")
        return ("block", body)

    def var_decl(self):
        kind = self.next().value
        declarators = []
        while True:
            target = self.binding_target()
            init = None
            if self.eat("punct", "="):
                init = self.assignment()
            declarators.append((target, init))
            if not self.eat("punct", ","):
                break
        return ("vardecl", kind, declarators)

    def binding_target(self):
        if self.at("punct", "{"):
            return self.object_pattern()
        if self.at("punct", "["):
            return self.array_pattern()
        return ("bind_ident", self.expect("ident").value)

    def object_pattern(self):
        self.expect("punct", "{")
        props = []
        while not self.at("punct", "}"):
            name = self.expect("ident").value
            alias = name
            if self.eat("punct", ":"):
                alias = self.expect("ident").value
            default = None
            if self.eat("punct", "="):
                default = self.assignment()
            props.append((name, alias, default))
            if not self.eat("punct", ","):
                break
        self.expect("punct", "}")
        return ("bind_object", props)

    def array_pattern(self):
        self.expect("punct", "[")
        elements = []
        while not self.at("punct", "]"):
            if self.at("punct", ","):
                elements.append(None)      # hole: ([, v]) =>
            else:
                elements.append(self.binding_target())
            if not self.eat("punct", ","):
                break
        self.expect("punct", "]")
        return ("bind_array", elements)

    def function_decl(self, is_async):
        self.expect("keyword", "function")
        name = self.expect("ident").value
        params = self.param_list()
        body = self.block()
        return ("funcdecl", name, params, body, is_async)

    def param_list(self):
        self.expect("punct", "(")
        params = []
        while not self.at("punct", ")"):
            if self.eat("punct", "..."):
                params.append(("rest", self.expect("ident").value))
            else:
                target = self.binding_target()
                default = None
                if self.eat("punct", "="):
                    default = self.assignment()
                params.append(("param", target, default))
            if not self.eat("punct", ","):
                break
        self.expect("punct", ")")
        return params

    def if_stmt(self):
        self.expect("keyword", "if")
        self.expect("punct", "(")
        test = self.expression()
        self.expect("punct", ")")
        then = self.statement()
        alt = None
        if self.eat("keyword", "else"):
            alt = self.statement()
        return ("if", test, then, alt)

    def for_stmt(self):
        self.expect("keyword", "for")
        self.expect("punct", "(")
        init = None
        if self.at("keyword") and self.peek().value in ("var", "let", "const"):
            decl_kind = self.peek().value
            save = self.pos
            decl = self.var_decl()
            if self.at("keyword", "of"):
                self.next()
                iterable = self.expression()
                self.expect("punct", ")")
                target = decl[2][0][0]
                return ("forof", decl_kind, target, iterable, self.statement())
            if self.at("keyword", "in"):
                self.error("for-in is unsupported — use Object.keys()")
            init = decl
            del save
        elif not self.at("punct", ";"):
            init = ("exprstmt", self.expression())
        self.expect("punct", ";")
        test = None if self.at("punct", ";") else self.expression()
        self.expect("punct", ";")
        update = None if self.at("punct", ")") else self.expression()
        self.expect("punct", ")")
        return ("for", init, test, update, self.statement())

    def try_stmt(self):
        self.expect("keyword", "try")
        block = self.block()
        handler = None
        finalizer = None
        if self.eat("keyword", "catch"):
            param = None
            if self.eat("punct", "("):
                param = self.expect("ident").value
                self.expect("punct", ")")
            handler = (param, self.block())
        if self.eat("keyword", "finally"):
            finalizer = self.block()
        return ("try", block, handler, finalizer)

    # -- expressions --------------------------------------------------------
    def expression(self):
        expr = self.assignment()
        while self.at("punct", ","):
            self.next()
            right = self.assignment()
            expr = ("comma", expr, right)
        return expr

    def assignment(self):
        arrow = self.try_arrow()
        if arrow is not None:
            return arrow
        left = self.conditional()
        if self.peek().kind == "punct" and self.peek().value in ASSIGN_OPS:
            op = self.next().value
            right = self.assignment()
            return ("assign", op, left, right)
        return left

    def try_arrow(self):
        """Detect `ident =>`, `async ident =>`, `(params) =>`."""
        start = self.pos
        is_async = False
        if self.at("keyword", "async") and self.peek(1).kind in ("ident", "punct"):
            if (self.peek(1).kind == "ident" and self.peek(2).kind == "punct"
                    and self.peek(2).value == "=>") or \
               (self.peek(1).kind == "punct" and self.peek(1).value == "("):
                probe = self.pos + 1
                if self.tokens[probe].value == "(":
                    close = self._matching_paren(probe)
                    if close is None or self.tokens[close + 1].value != "=>":
                        probe = None
                if probe is not None:
                    is_async = True
                    self.next()
        token = self.peek()
        if token.kind == "ident" and self.peek(1).kind == "punct" \
                and self.peek(1).value == "=>":
            name = self.next().value
            self.next()
            return self.arrow_body([("param", ("bind_ident", name), None)], is_async)
        if token.kind == "punct" and token.value == "(":
            close = self._matching_paren(self.pos)
            if close is not None and self.tokens[close + 1].kind == "punct" \
                    and self.tokens[close + 1].value == "=>":
                params = self.param_list()
                self.expect("punct", "=>")
                return self.arrow_body(params, is_async)
        self.pos = start
        return None

    def _matching_paren(self, open_pos):
        depth = 0
        for index in range(open_pos, len(self.tokens)):
            value = self.tokens[index].value
            if value in ("(", "[", "{"):
                depth += 1
            elif value in (")", "]", "}"):
                depth -= 1
                if depth == 0:
                    return index
        return None

    def arrow_body(self, params, is_async):
        if self.at("punct", "{"):
            return ("arrow", params, self.block(), is_async)
        expr = self.assignment()
        return ("arrow", params, ("return", expr), is_async)

    def conditional(self):
        test = self.nullish()
        if self.eat("punct", "?"):
            consequent = self.assignment()
            self.expect("punct", ":")
            alternate = self.assignment()
            return ("ternary", test, consequent, alternate)
        return test

    def nullish(self):
        left = self.logical_or()
        while self.at("punct", "??"):
            self.next()
            left = ("nullish", left, self.logical_or())
        return left

    def logical_or(self):
        left = self.logical_and()
        while self.at("punct", "||"):
            self.next()
            left = ("or", left, self.logical_and())
        return left

    def logical_and(self):
        left = self.equality()
        while self.at("punct", "&&"):
            self.next()
            left = ("and", left, self.equality())
        return left

    def equality(self):
        left = self.relational()
        while self.peek().kind == "punct" and self.peek().value in \
                ("==", "!=", "===", "!=="):
            op = self.next().value
            left = ("binary", op, left, self.relational())
        return left

    def relational(self):
        left = self.additive()
        while (self.peek().kind == "punct" and self.peek().value in
               ("<", ">", "<=", ">=")) or self.at("keyword", "instanceof"):
            if self.at("keyword", "instanceof"):
                self.next()
                left = ("instanceof", left, self.additive())
            else:
                op = self.next().value
                left = ("binary", op, left, self.additive())
        return left

    def additive(self):
        left = self.multiplicative()
        while self.peek().kind == "punct" and self.peek().value in ("+", "-"):
            op = self.next().value
            left = ("binary", op, left, self.multiplicative())
        return left

    def multiplicative(self):
        left = self.unary()
        while self.peek().kind == "punct" and self.peek().value in ("*", "/", "%"):
            op = self.next().value
            left = ("binary", op, left, self.unary())
        return left

    def unary(self):
        token = self.peek()
        if token.kind == "punct" and token.value in ("!", "-", "+", "~"):
            self.next()
            return ("unary", token.value, self.unary())
        if token.kind == "punct" and token.value in ("++", "--"):
            self.next()
            return ("update", token.value, self.unary(), True)
        if token.kind == "keyword" and token.value in ("typeof", "delete", "void"):
            self.next()
            return ("unary", token.value, self.unary())
        if token.kind == "keyword" and token.value == "await":
            self.next()
            return ("await", self.unary())
        if token.kind == "keyword" and token.value == "new":
            self.next()
            callee = self.member_chain(self.primary(), allow_calls=False)
            args = self.arguments() if self.at("punct", "(") else []
            return self.postfix(self.member_chain(("new", callee, args)))
        return self.postfix(self.member_chain(self.primary()))

    def postfix(self, expr):
        if self.peek().kind == "punct" and self.peek().value in ("++", "--"):
            op = self.next().value
            return ("update", op, expr, False)
        return expr

    def member_chain(self, expr, allow_calls=True):
        while True:
            if self.at("punct", "."):
                self.next()
                name = self.next()
                if name.kind not in ("ident", "keyword"):
                    self.error("expected property name")
                expr = ("member", expr, ("lit", name.value))
            elif self.at("punct", "["):
                self.next()
                prop = self.expression()
                self.expect("punct", "]")
                expr = ("member", expr, prop)
            elif allow_calls and self.at("punct", "("):
                expr = ("call", expr, self.arguments())
            elif self.at("template"):
                self.error("tagged templates are unsupported")
            else:
                return expr

    def arguments(self):
        self.expect("punct", "(")
        args = []
        while not self.at("punct", ")"):
            if self.eat("punct", "..."):
                args.append(("spread", self.assignment()))
            else:
                args.append(self.assignment())
            if not self.eat("punct", ","):
                break
        self.expect("punct", ")")
        return args

    def primary(self):
        token = self.peek()
        if token.kind == "num":
            self.next()
            return ("lit", token.value)
        if token.kind == "str":
            self.next()
            return ("lit", token.value)
        if token.kind == "regex":
            self.next()
            return ("regexlit", token.value[0], token.value[1])
        if token.kind == "template":
            self.next()
            parts, raw_exprs = token.value
            exprs = []
            for raw in raw_exprs:
                sub = Parser(tokenize(raw, self.filename), self.filename)
                exprs.append(sub.expression())
                if not sub.at("eof"):
                    sub.error("trailing tokens in template expression")
            return ("template", parts, exprs)
        if token.kind == "ident":
            self.next()
            return ("ident", token.value)
        if token.kind == "keyword":
            word = token.value
            if word in ("true", "false"):
                self.next()
                return ("lit", word == "true")
            if word == "null":
                self.next()
                return ("lit", NULL)
            if word == "undefined":
                self.next()
                return ("lit", UNDEFINED)
            if word == "this":
                self.next()
                return ("this",)
            if word == "function":
                self.next()
                name = self.eat("ident")
                params = self.param_list()
                body = self.block()
                return ("funcexpr", name.value if name else None, params, body, False)
            if word == "async" and self.peek(1).kind == "keyword" \
                    and self.peek(1).value == "function":
                self.next()
                self.next()
                name = self.eat("ident")
                params = self.param_list()
                body = self.block()
                return ("funcexpr", name.value if name else None, params, body, True)
            self.error(f"unexpected keyword {word!r}")
        if token.kind == "punct":
            if token.value == "(":
                self.next()
                expr = self.expression()
                self.expect("punct", ")")
                return expr
            if token.value == "[":
                self.next()
                elements = []
                while not self.at("punct", "]"):
                    if self.eat("punct", "..."):
                        elements.append(("spread", self.assignment()))
                    else:
                        elements.append(self.assignment())
                    if not self.eat("punct", ","):
                        break
                self.expect("punct", "]")
                return ("array", elements)
            if token.value == "{":
                return self.object_literal()
        self.error(f"unexpected token {token.value!r}")

    def object_literal(self):
        self.expect("punct", "{")
        props = []
        while not self.at("punct", "}"):
            key_token = self.next()
            if key_token.kind in ("ident", "keyword"):
                key = ("lit", key_token.value)
            elif key_token.kind == "str":
                key = ("lit", key_token.value)
            elif key_token.kind == "num":
                key = ("lit", js_str(key_token.value))
            elif key_token.kind == "punct" and key_token.value == "[":
                key = self.assignment()
                self.expect("punct", "]")
            else:
                self.error(f"unsupported object key {key_token.value!r}")
            if self.eat("punct", ":"):
                props.append((key, self.assignment()))
            elif self.at("punct", "(") and key_token.kind in ("ident", "keyword"):
                params = self.param_list()
                body = self.block()
                props.append((key, ("funcexpr", key_token.value, params, body, False)))
            else:
                if key_token.kind not in ("ident", "keyword"):
                    self.error("shorthand property must be an identifier")
                props.append((key, ("ident", key_token.value)))
            if not self.eat("punct", ","):
                break
        self.expect("punct", "}")
        return ("object", props)


# ---------------------------------------------------------------------------
# runtime values
# ---------------------------------------------------------------------------


class JSObject:
    """Plain object: insertion-ordered property dict."""

    def __init__(self, props: Optional[Dict[str, Any]] = None):
        self.props: Dict[str, Any] = dict(props or {})

    def get(self, name):
        return self.props.get(name, UNDEFINED)

    def set(self, name, value):
        self.props[name] = value

    def __repr__(self):
        return "[object Object]"


class JSArray:
    def __init__(self, items: Optional[List[Any]] = None):
        self.items: List[Any] = list(items or [])

    def __repr__(self):
        return js_str(self)


class JSFunction:
    def __init__(self, name, params, body, closure, interpreter, is_async,
                 this=UNDEFINED):
        self.name = name or "<anonymous>"
        self.params = params
        self.body = body
        self.closure = closure
        self.interpreter = interpreter
        self.is_async = is_async
        self.this = this

    def __call__(self, *args, this=None):
        return self.interpreter.call_function(
            self, list(args), this if this is not None else self.this)


class JSPromise:
    """Synchronous promise: settled at construction (the UI has no real
    concurrency — fetch resolves inline through the WSGI bridge)."""

    def __init__(self, value=UNDEFINED, error=None):
        self.value = value
        self.error = error      # JSException or None

    @classmethod
    def resolve(cls, value):
        return value if isinstance(value, JSPromise) else cls(value=value)

    @classmethod
    def reject(cls, exc: JSException):
        return cls(error=exc)


class JSRegex:
    def __init__(self, pattern: str, flags: str):
        self.source = pattern
        self.flags = flags
        py_flags = _re.IGNORECASE if "i" in flags else 0
        self.compiled = _re.compile(_js_regex_to_python(pattern), py_flags)
        self.global_ = "g" in flags


def _js_regex_to_python(pattern: str) -> str:
    # the UI's patterns are simple char classes / escapes; python re accepts
    # them as-is except JS-only escapes we don't use
    return pattern


EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)


class JSDate:
    """JS Date over UTC (the test environment pins UTC: getTimezoneOffset
    is 0, so local == UTC and toLocaleString is deterministic). Month/day
    overflow normalizes exactly like JS MakeDay (setMonth(12) → January of
    the next year; day 32 rolls into the next month)."""

    def __init__(self, ms: float):
        self.ms = float(ms)

    #: tests pin "now" so date-boundary behavior (month-view anchoring in a
    #: partial first week, year rollover) is reproducible on any day
    fixed_now_ms: Optional[float] = None

    # -- construction -------------------------------------------------------
    @classmethod
    def now(cls):
        if cls.fixed_now_ms is not None:
            return cls(cls.fixed_now_ms)
        return cls((datetime.now(timezone.utc) - EPOCH).total_seconds() * 1000)

    @classmethod
    def from_parts(cls, year, month, day=1, hours=0, minutes=0, seconds=0, ms=0):
        year_extra, month = divmod(int(month), 12)
        base = datetime(int(year) + year_extra, month + 1, 1, tzinfo=timezone.utc)
        delta = timedelta(days=int(day) - 1, hours=int(hours),
                          minutes=int(minutes), seconds=int(seconds),
                          milliseconds=int(ms))
        return cls(((base - EPOCH) + delta).total_seconds() * 1000)

    @classmethod
    def parse(cls, text: str):
        text = text.strip()
        for fmt in ("%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z"):
            try:
                return cls((datetime.strptime(text.replace("Z", "+0000"), fmt)
                            - EPOCH).total_seconds() * 1000)
            except ValueError:
                pass
        for fmt in ("%Y-%m-%dT%H:%M:%S.%f", "%Y-%m-%dT%H:%M:%S",
                    "%Y-%m-%dT%H:%M", "%Y-%m-%d"):
            try:
                value = datetime.strptime(text, fmt).replace(tzinfo=timezone.utc)
                return cls((value - EPOCH).total_seconds() * 1000)
            except ValueError:
                pass
        raise JSError(f"unsupported Date string {text!r}")

    # -- accessors ----------------------------------------------------------
    def _dt(self) -> datetime:
        return EPOCH + timedelta(milliseconds=self.ms)

    def getFullYear(self):
        return float(self._dt().year)

    def getMonth(self):
        return float(self._dt().month - 1)

    def getDate(self):
        return float(self._dt().day)

    def getDay(self):
        return float((self._dt().weekday() + 1) % 7)   # JS: Sunday = 0

    def getHours(self):
        return float(self._dt().hour)

    def getMinutes(self):
        return float(self._dt().minute)

    def getTime(self):
        return self.ms

    def getTimezoneOffset(self):
        return 0.0

    # -- mutators (JS-normalizing) -----------------------------------------
    def _rebuild(self, **overrides):
        current = self._dt()
        parts = dict(year=current.year, month=current.month - 1,
                     day=current.day, hours=current.hour,
                     minutes=current.minute, seconds=current.second,
                     ms=current.microsecond // 1000)
        parts.update(overrides)
        self.ms = JSDate.from_parts(**parts).ms
        return self.ms

    def setHours(self, hours, minutes=None, seconds=None, ms=None):
        overrides = {"hours": hours}
        if minutes is not None:
            overrides["minutes"] = minutes
        if seconds is not None:
            overrides["seconds"] = seconds
        if ms is not None:
            overrides["ms"] = ms
        return self._rebuild(**overrides)

    def setMinutes(self, minutes, seconds=None, ms=None):
        overrides = {"minutes": minutes}
        if seconds is not None:
            overrides["seconds"] = seconds
        if ms is not None:
            overrides["ms"] = ms
        return self._rebuild(**overrides)

    def setDate(self, day):
        return self._rebuild(day=day)

    def setMonth(self, month):
        return self._rebuild(month=month)

    def setFullYear(self, year):
        return self._rebuild(year=year)

    # -- formatting ---------------------------------------------------------
    def toISOString(self):
        dt = self._dt()
        return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"

    def toDateString(self):
        dt = self._dt()
        days = ["Sun", "Mon", "Tue", "Wed", "Thu", "Fri", "Sat"]
        months = ["Jan", "Feb", "Mar", "Apr", "May", "Jun", "Jul", "Aug",
                  "Sep", "Oct", "Nov", "Dec"]
        return (f"{days[int(self.getDay())]} {months[dt.month - 1]} "
                f"{dt.day:02d} {dt.year}")

    def toLocaleDateString(self, _locale=UNDEFINED, options=None):
        dt = self._dt()
        months = ["January", "February", "March", "April", "May", "June",
                  "July", "August", "September", "October", "November",
                  "December"]
        if options is not None and isinstance(options, JSObject) and \
                options.get("month") == "long":
            return f"{months[dt.month - 1]} {dt.year}"
        return f"{dt.month}/{dt.day}/{dt.year}"

    def toLocaleString(self, _locale=UNDEFINED, _options=None):
        dt = self._dt()
        return f"{dt.month}/{dt.day}/{dt.year[-2:] if False else dt.year % 100:02d}, {dt.hour:02d}:{dt.minute:02d}"

    def __repr__(self):
        return self.toISOString()


class JSSet:
    def __init__(self, items=None):
        self._items: Dict[Any, None] = {}
        for item in items or []:
            self._items[item] = None

    def add(self, value):
        self._items[value] = None
        return self

    def delete(self, value):
        return self._items.pop(value, "__missing__") != "__missing__"

    def has(self, value):
        return value in self._items

    @property
    def size(self):
        return float(len(self._items))

    def __iter__(self):
        return iter(self._items)


# ---------------------------------------------------------------------------
# coercions
# ---------------------------------------------------------------------------


def js_truthy(value) -> bool:
    if value is UNDEFINED or value is NULL:
        return False
    if isinstance(value, bool):
        return value
    if isinstance(value, float):
        return not (value == 0 or math.isnan(value))
    if isinstance(value, str):
        return value != ""
    return True


def js_number(value) -> float:
    if isinstance(value, bool):
        return 1.0 if value else 0.0
    if isinstance(value, float):
        return value
    if value is NULL:
        return 0.0
    if value is UNDEFINED:
        return math.nan
    if isinstance(value, str):
        text = value.strip()
        if text == "":
            return 0.0
        try:
            return float(text)
        except ValueError:
            return math.nan
    if isinstance(value, JSDate):
        return value.ms
    if isinstance(value, JSArray):
        if not value.items:
            return 0.0
        if len(value.items) == 1:
            return js_number(value.items[0])
    return math.nan


def js_str(value) -> str:
    if value is UNDEFINED:
        return "undefined"
    if value is NULL:
        return "null"
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float):
        if math.isnan(value):
            return "NaN"
        if math.isinf(value):
            return "Infinity" if value > 0 else "-Infinity"
        if value == int(value) and abs(value) < 1e21:
            return str(int(value))
        return repr(value)
    if isinstance(value, str):
        return value
    if isinstance(value, JSArray):
        return ",".join("" if item in (UNDEFINED, NULL) else js_str(item)
                        for item in value.items)
    if isinstance(value, JSDate):
        return value.toISOString()
    if isinstance(value, JSObject):
        return "[object Object]"
    if isinstance(value, (JSFunction,)) or callable(value):
        return f"function {getattr(value, 'name', '')}() {{ [code] }}"
    return str(value)


def js_equals_loose(a, b) -> bool:
    if (a is NULL or a is UNDEFINED) and (b is NULL or b is UNDEFINED):
        return True
    if a is NULL or a is UNDEFINED or b is NULL or b is UNDEFINED:
        return False
    if type(a) is type(b) or (isinstance(a, (float, bool)) and
                              isinstance(b, (float, bool))):
        return js_equals_strict(a, b)
    if isinstance(a, str) and isinstance(b, float):
        return js_number(a) == b
    if isinstance(a, float) and isinstance(b, str):
        return a == js_number(b)
    if isinstance(a, (JSDate,)) or isinstance(b, (JSDate,)):
        return js_number(a) == js_number(b)
    return js_equals_strict(a, b)


def js_equals_strict(a, b) -> bool:
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, float) and isinstance(b, float):
        return a == b
    if type(a) is not type(b):
        return False
    if isinstance(a, (str, bool, float)):
        return a == b
    return a is b


# ---------------------------------------------------------------------------
# environment
# ---------------------------------------------------------------------------


class Environment:
    def __init__(self, parent: Optional["Environment"] = None):
        self.vars: Dict[str, Any] = {}
        self.parent = parent

    def declare(self, name, value):
        self.vars[name] = value

    def get(self, name):
        env = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        raise JSException(_make_error(f"{name} is not defined",
                                      kind="ReferenceError"))

    def set(self, name, value):
        env = self
        while env is not None:
            if name in env.vars:
                env.vars[name] = value
                return
            env = env.parent
        # sloppy-mode implicit global (the UI runs "use strict" but never
        # assigns undeclared names; still, fail loud)
        raise JSException(_make_error(f"{name} is not defined",
                                      kind="ReferenceError"))

    def has(self, name):
        env = self
        while env is not None:
            if name in env.vars:
                return True
            env = env.parent
        return False


def _make_error(message, kind="Error"):
    obj = JSObject({"name": kind, "message": message})
    obj.is_error = True
    return obj


# control-flow signals
class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


# ---------------------------------------------------------------------------
# interpreter
# ---------------------------------------------------------------------------


class Interpreter:
    def __init__(self):
        self.global_env = Environment()
        self._setup_globals()
        self._call_depth = 0

    # -- public API ---------------------------------------------------------
    def run(self, source: str, filename: str = "<js>"):
        program = Parser(tokenize(source, filename), filename).parse_program()
        self._hoist(program[1], self.global_env)
        result = UNDEFINED
        for stmt in program[1]:
            result = self.exec_stmt(stmt, self.global_env)
        return result

    def eval_expr(self, source: str, extra_env: Optional[Dict[str, Any]] = None):
        parser = Parser(tokenize(source, "<eval>"), "<eval>")
        env = Environment(self.global_env)
        for key, value in (extra_env or {}).items():
            env.declare(key, value)
        result = UNDEFINED
        while not parser.at("eof"):
            stmt = parser.statement()
            result = self.exec_stmt(stmt, env)
        return result

    def define(self, name, value):
        self.global_env.declare(name, value)

    # -- statements ---------------------------------------------------------
    def _hoist(self, body, env):
        for stmt in body:
            if stmt[0] == "funcdecl":
                _, name, params, fbody, is_async = stmt
                env.declare(name, JSFunction(name, params, fbody, env, self,
                                             is_async))

    def exec_stmt(self, node, env):
        kind = node[0]
        if kind == "exprstmt":
            return self.eval(node[1], env)
        if kind == "vardecl":
            for target, init in node[2]:
                value = self.eval(init, env) if init is not None else UNDEFINED
                self._bind(target, value, env, declare=True)
            return UNDEFINED
        if kind == "funcdecl":
            _, name, params, body, is_async = node
            env.declare(name, JSFunction(name, params, body, env, self, is_async))
            return UNDEFINED
        if kind == "if":
            _, test, then, alt = node
            if js_truthy(self.eval(test, env)):
                return self.exec_stmt(then, Environment(env))
            if alt is not None:
                return self.exec_stmt(alt, Environment(env))
            return UNDEFINED
        if kind == "block":
            inner = Environment(env)
            self._hoist(node[1], inner)
            for stmt in node[1]:
                self.exec_stmt(stmt, inner)
            return UNDEFINED
        if kind == "for":
            _, init, test, update, body = node
            loop_env = Environment(env)
            if init is not None:
                self.exec_stmt(init, loop_env)
            while test is None or js_truthy(self.eval(test, loop_env)):
                try:
                    self.exec_stmt(body, Environment(loop_env))
                except _Break:
                    break
                except _Continue:
                    pass
                if update is not None:
                    self.eval(update, loop_env)
            return UNDEFINED
        if kind == "forof":
            _, _, target, iterable, body = node
            for item in self._iterate(self.eval(iterable, env)):
                inner = Environment(env)
                self._bind(target, item, inner, declare=True)
                try:
                    self.exec_stmt(body, inner)
                except _Break:
                    break
                except _Continue:
                    continue
            return UNDEFINED
        if kind == "while":
            _, test, body = node
            while js_truthy(self.eval(test, env)):
                try:
                    self.exec_stmt(body, Environment(env))
                except _Break:
                    break
                except _Continue:
                    continue
            return UNDEFINED
        if kind == "return":
            raise _Return(self.eval(node[1], env) if node[1] is not None
                          else UNDEFINED)
        if kind == "throw":
            raise JSException(self.eval(node[1], env))
        if kind == "break":
            raise _Break()
        if kind == "continue":
            raise _Continue()
        if kind == "try":
            _, block, handler, finalizer = node
            try:
                self.exec_stmt(block, Environment(env))
            except JSException as exc:
                if handler is not None:
                    param, hblock = handler
                    inner = Environment(env)
                    if param:
                        inner.declare(param, exc.value)
                    self.exec_stmt(hblock, inner)
                elif finalizer is None:
                    raise
            finally:
                if finalizer is not None:
                    self.exec_stmt(finalizer, Environment(env))
            return UNDEFINED
        if kind == "empty":
            return UNDEFINED
        raise JSError(f"unsupported statement {kind}")

    # -- expressions --------------------------------------------------------
    def eval(self, node, env):
        kind = node[0]
        if kind == "lit":
            return node[1]
        if kind == "ident":
            return env.get(node[1])
        if kind == "this":
            return env.get("this") if env.has("this") else UNDEFINED
        if kind == "template":
            _, parts, exprs = node
            out = [parts[0]]
            for expr, part in zip(exprs, parts[1:]):
                out.append(js_str(self.eval(expr, env)))
                out.append(part)
            return "".join(out)
        if kind == "array":
            items = []
            for element in node[1]:
                if element[0] == "spread":
                    items.extend(self._iterate(self.eval(element[1], env)))
                else:
                    items.append(self.eval(element, env))
            return JSArray(items)
        if kind == "object":
            obj = JSObject()
            for key_node, value_node in node[1]:
                key = js_str(self.eval(key_node, env))
                obj.set(key, self.eval(value_node, env))
            return obj
        if kind == "regexlit":
            return JSRegex(node[1], node[2])
        if kind == "arrow":
            _, params, body, is_async = node
            return JSFunction(None, params, body, env, self, is_async)
        if kind == "funcexpr":
            _, name, params, body, is_async = node
            return JSFunction(name, params, body, env, self, is_async)
        if kind == "assign":
            return self._assign(node, env)
        if kind == "update":
            return self._update(node, env)
        if kind == "ternary":
            _, test, cons, alt = node
            return self.eval(cons if js_truthy(self.eval(test, env)) else alt, env)
        if kind == "and":
            left = self.eval(node[1], env)
            return self.eval(node[2], env) if js_truthy(left) else left
        if kind == "or":
            left = self.eval(node[1], env)
            return left if js_truthy(left) else self.eval(node[2], env)
        if kind == "nullish":
            left = self.eval(node[1], env)
            return self.eval(node[2], env) if left is NULL or left is UNDEFINED \
                else left
        if kind == "binary":
            return self._binary(node[1], self.eval(node[2], env),
                                self.eval(node[3], env))
        if kind == "instanceof":
            left = self.eval(node[1], env)
            right = self.eval(node[2], env)
            ctor_map = {"Date": JSDate, "Set": JSSet, "Array": JSArray,
                        "Error": JSObject}
            for name, pytype in ctor_map.items():
                if right is self.global_env.vars.get(name):
                    return isinstance(left, pytype)
            return False
        if kind == "unary":
            op = node[1]
            if op == "typeof":
                try:
                    value = self.eval(node[2], env)
                except JSException:
                    return "undefined"
                return _typeof(value)
            if op == "delete":
                target = node[2]
                if target[0] == "member":
                    obj = self.eval(target[1], env)
                    prop = js_str(self.eval(target[2], env))
                    self._delete_prop(obj, prop)
                    return True
                return True
            value = self.eval(node[2], env)
            if op == "!":
                return not js_truthy(value)
            if op == "-":
                return -js_number(value)
            if op == "+":
                return js_number(value)
            if op == "void":
                return UNDEFINED
            if op == "~":
                return float(~_to_int32(value))
            raise JSError(f"unsupported unary {op}")
        if kind == "member":
            obj = self.eval(node[1], env)
            prop = js_str(self.eval(node[2], env))
            return self.get_property(obj, prop)
        if kind == "call":
            return self._call(node, env)
        if kind == "new":
            _, callee_node, arg_nodes = node
            callee = self.eval(callee_node, env)
            args = self._eval_args(arg_nodes, env)
            return self._construct(callee, args)
        if kind == "await":
            value = self.eval(node[1], env)
            if isinstance(value, JSPromise):
                if value.error is not None:
                    raise value.error
                return value.value
            return value
        if kind == "comma":
            self.eval(node[1], env)
            return self.eval(node[2], env)
        if kind == "spread":
            raise JSError("spread outside call/array")
        raise JSError(f"unsupported expression {kind}")

    # -- helpers ------------------------------------------------------------
    def _iterate(self, value):
        if isinstance(value, JSArray):
            return list(value.items)
        if isinstance(value, JSSet):
            return list(value)
        if isinstance(value, str):
            return list(value)
        if isinstance(value, JSObject) and "length" in value.props:
            length = int(js_number(value.get("length")))
            return [value.get(js_str(float(i))) for i in range(length)]
        if hasattr(value, "js_iterate"):
            return list(value.js_iterate())
        raise JSException(_make_error(
            f"{js_str(value)} is not iterable", kind="TypeError"))

    def _bind(self, target, value, env, declare):
        kind = target[0]
        if kind == "bind_ident":
            if declare:
                env.declare(target[1], value)
            else:
                env.set(target[1], value)
            return
        if kind == "bind_object":
            for name, alias, default in target[1]:
                item = self.get_property(value, name)
                if item is UNDEFINED and default is not None:
                    item = self.eval(default, env)
                if declare:
                    env.declare(alias, item)
                else:
                    env.set(alias, item)
            return
        if kind == "bind_array":
            items = self._iterate(value)
            for index, element in enumerate(target[1]):
                if element is None:
                    continue
                item = items[index] if index < len(items) else UNDEFINED
                self._bind(element, item, env, declare)
            return
        raise JSError(f"unsupported binding target {kind}")

    def _assign(self, node, env):
        _, op, left, right = node
        if op != "=":
            current = self.eval(left, env)
            value = self._binary(op[0], current, self.eval(right, env))
        else:
            value = self.eval(right, env)
        self._store(left, value, env)
        return value

    def _store(self, target, value, env):
        kind = target[0]
        if kind == "ident":
            env.set(target[1], value)
            return
        if kind == "member":
            obj = self.eval(target[1], env)
            prop = js_str(self.eval(target[2], env))
            self.set_property(obj, prop, value)
            return
        if kind == "array":
            items = self._iterate(value)
            for index, element in enumerate(target[1]):
                item = items[index] if index < len(items) else UNDEFINED
                self._store(element, item, env)
            return
        raise JSError(f"invalid assignment target {kind}")

    def _update(self, node, env):
        _, op, target, prefix = node
        current = js_number(self.eval(target, env))
        updated = current + (1 if op == "++" else -1)
        self._store(target, updated, env)
        return updated if prefix else current

    def _binary(self, op, left, right):
        if op == "+":
            lprim = _to_primitive(left)
            rprim = _to_primitive(right)
            if isinstance(lprim, str) or isinstance(rprim, str):
                return js_str(lprim) + js_str(rprim)
            return js_number(lprim) + js_number(rprim)
        if op == "-":
            return js_number(left) - js_number(right)
        if op == "*":
            return js_number(left) * js_number(right)
        if op == "/":
            rnum = js_number(right)
            lnum = js_number(left)
            if rnum == 0:
                if lnum == 0 or math.isnan(lnum):
                    return math.nan
                return math.inf if (lnum > 0) == (rnum >= 0) else -math.inf
            return lnum / rnum
        if op == "%":
            rnum = js_number(right)
            lnum = js_number(left)
            if rnum == 0 or math.isnan(lnum) or math.isnan(rnum):
                return math.nan
            return math.fmod(lnum, rnum)
        if op == "==":
            return js_equals_loose(left, right)
        if op == "!=":
            return not js_equals_loose(left, right)
        if op == "===":
            return js_equals_strict(left, right)
        if op == "!==":
            return not js_equals_strict(left, right)
        if op in ("<", ">", "<=", ">="):
            lprim = _to_primitive(left, hint="number")
            rprim = _to_primitive(right, hint="number")
            if isinstance(lprim, str) and isinstance(rprim, str):
                pass
            else:
                lprim, rprim = js_number(lprim), js_number(rprim)
                if math.isnan(lprim) or math.isnan(rprim):
                    return False
            if op == "<":
                return lprim < rprim
            if op == ">":
                return lprim > rprim
            if op == "<=":
                return lprim <= rprim
            return lprim >= rprim
        raise JSError(f"unsupported operator {op}")

    def _eval_args(self, arg_nodes, env):
        args = []
        for arg in arg_nodes:
            if arg[0] == "spread":
                args.extend(self._iterate(self.eval(arg[1], env)))
            else:
                args.append(self.eval(arg, env))
        return args

    def _call(self, node, env):
        _, callee_node, arg_nodes = node
        this = UNDEFINED
        if callee_node[0] == "member":
            obj = self.eval(callee_node[1], env)
            prop = js_str(self.eval(callee_node[2], env))
            func = self.get_property(obj, prop)
            this = obj
        else:
            func = self.eval(callee_node, env)
        args = self._eval_args(arg_nodes, env)
        return self.call_any(func, args, this,
                             name=_callee_name(callee_node))

    def call_any(self, func, args, this=UNDEFINED, name="<expr>"):
        if isinstance(func, JSFunction):
            return self.call_function(func, args, this)
        if callable(func):
            args = _trim_args(func, args)
            return func(*args) if not _wants_this(func) else func(this, *args)
        raise JSException(_make_error(f"{name} is not a function",
                                      kind="TypeError"))

    def call_function(self, func: JSFunction, args: List[Any], this):
        self._call_depth += 1
        if self._call_depth > 400:
            self._call_depth -= 1
            raise JSError("call depth limit exceeded (runaway recursion?)")
        try:
            env = Environment(func.closure)
            env.declare("this", this)
            index = 0
            for param in func.params:
                if param[0] == "rest":
                    env.declare(param[1], JSArray(args[index:]))
                    break
                _, target, default = param
                value = args[index] if index < len(args) else UNDEFINED
                if value is UNDEFINED and default is not None:
                    value = self.eval(default, env)
                self._bind(target, value, env, declare=True)
                index += 1
            try:
                body = func.body
                if body[0] == "block":
                    self._hoist(body[1], env)
                    for stmt in body[1]:
                        self.exec_stmt(stmt, env)
                    result = UNDEFINED
                else:
                    self.exec_stmt(body, env)
                    result = UNDEFINED
            except _Return as ret:
                result = ret.value
            if func.is_async:
                return JSPromise.resolve(result)
            return result
        except JSException as exc:
            if func.is_async:
                return JSPromise.reject(exc)
            raise
        finally:
            self._call_depth -= 1

    def _construct(self, callee, args):
        ctor = getattr(callee, "js_construct", None)
        if ctor is not None:
            return ctor(*args)
        if isinstance(callee, JSFunction):
            this = JSObject()
            result = self.call_function(callee, args, this)
            return result if isinstance(result, JSObject) else this
        raise JSError(f"cannot construct {js_str(callee)}")

    # -- property access ----------------------------------------------------
    def get_property(self, obj, prop):
        if obj is UNDEFINED or obj is NULL:
            raise JSException(_make_error(
                f"cannot read properties of {js_str(obj)} (reading '{prop}')",
                kind="TypeError"))
        # host objects (DOM nodes etc.) implement their own protocol
        getter = getattr(obj, "js_get", None)
        if getter is not None:
            return getter(prop)
        if isinstance(obj, JSObject):
            if prop in obj.props:
                return obj.props[prop]
            return UNDEFINED
        if isinstance(obj, JSArray):
            return self._array_property(obj, prop)
        if isinstance(obj, str):
            return self._string_property(obj, prop)
        if isinstance(obj, float):
            return self._number_property(obj, prop)
        if isinstance(obj, JSDate):
            method = getattr(obj, prop, None)
            if method is None:
                raise JSError(f"Date.{prop} is unsupported — extend tools/minijs.py")
            return _native(lambda *a: _jsnum(method(*a)))
        if isinstance(obj, JSSet):
            if prop == "size":
                return obj.size
            if prop in ("add", "delete", "has"):
                return _native(getattr(obj, prop))
            raise JSError(f"Set.{prop} is unsupported")
        if isinstance(obj, JSPromise):
            if prop == "then":
                return _native(lambda fn=None, *_: self._promise_then(obj, fn))
            if prop == "catch":
                return _native(lambda fn=None, *_: self._promise_catch(obj, fn))
            if prop == "finally":
                return _native(lambda fn=None, *_:
                               (fn and self.call_any(fn, []), obj)[1])
            raise JSError(f"Promise.{prop} is unsupported")
        if isinstance(obj, JSRegex):
            if prop == "test":
                return _native(lambda s="": obj.compiled.search(js_str(s)) is not None)
            if prop == "source":
                return obj.source
            raise JSError(f"RegExp.{prop} is unsupported")
        if isinstance(obj, JSFunction) or callable(obj):
            if prop == "name":
                return getattr(obj, "name", "")
            if prop == "call":
                return _native(lambda this=UNDEFINED, *args:
                               self.call_any(obj, list(args), this))
            if prop == "apply":
                return _native(lambda this=UNDEFINED, args=None:
                               self.call_any(obj, list(args.items) if
                                             isinstance(args, JSArray) else [],
                                             this))
            if prop == "bind":
                return _native(lambda this=UNDEFINED, *pre: _native(
                    lambda *args: self.call_any(obj, list(pre) + list(args), this)))
            extra = getattr(obj, "js_props", None)
            if extra is not None and prop in extra:
                return extra[prop]
            return UNDEFINED
        if isinstance(obj, bool):
            raise JSError(f"boolean has no property {prop!r}")
        raise JSError(f"cannot read {prop!r} of {type(obj).__name__}")

    def set_property(self, obj, prop, value):
        setter = getattr(obj, "js_set", None)
        if setter is not None:
            setter(prop, value)
            return
        if isinstance(obj, JSObject):
            obj.props[prop] = value
            return
        if isinstance(obj, JSArray):
            if prop == "length":
                length = int(js_number(value))
                del obj.items[length:]
                obj.items.extend([UNDEFINED] * (length - len(obj.items)))
                return
            try:
                index = int(prop)
            except ValueError:
                raise JSError(f"cannot set array property {prop!r}")
            while len(obj.items) <= index:
                obj.items.append(UNDEFINED)
            obj.items[index] = value
            return
        if isinstance(obj, (JSFunction,)) or callable(obj):
            props = getattr(obj, "js_props", None)
            if props is None:
                try:
                    obj.js_props = props = {}
                except AttributeError:
                    raise JSError("cannot set properties on this native function")
            props[prop] = value
            return
        raise JSError(f"cannot set {prop!r} on {type(obj).__name__}")

    def _delete_prop(self, obj, prop):
        deleter = getattr(obj, "js_delete", None)
        if deleter is not None:
            deleter(prop)
            return
        if isinstance(obj, JSObject):
            obj.props.pop(prop, None)
            return
        raise JSError(f"cannot delete {prop!r} on {type(obj).__name__}")

    # -- promises -----------------------------------------------------------
    def _promise_then(self, promise, on_fulfilled):
        if promise.error is not None:
            return promise
        if on_fulfilled in (None, UNDEFINED, NULL):
            return promise
        try:
            return JSPromise.resolve(self.call_any(on_fulfilled, [promise.value]))
        except JSException as exc:
            return JSPromise.reject(exc)

    def _promise_catch(self, promise, on_rejected):
        if promise.error is None:
            return promise
        if on_rejected in (None, UNDEFINED, NULL):
            return promise
        try:
            return JSPromise.resolve(
                self.call_any(on_rejected, [promise.error.value]))
        except JSException as exc:
            return JSPromise.reject(exc)

    # -- array / string / number methods -------------------------------------
    def _array_property(self, arr: JSArray, prop):
        items = arr.items
        if prop == "length":
            return float(len(items))
        try:
            index = int(prop)
            if 0 <= index < len(items):
                return items[index]
            if str(index) == prop:
                return UNDEFINED
        except ValueError:
            pass
        call = self.call_any

        def method_map(fn, with_index=True):
            def runner(callback, *_):
                out = []
                for i, item in enumerate(items):
                    args = [item, float(i)] if with_index else [item]
                    out.append(call(callback, args))
                return fn(out)
            return _native(runner)

        table = {
            "map": method_map(JSArray),
            "forEach": method_map(lambda out: UNDEFINED),
            "filter": _native(lambda cb, *_: JSArray(
                [item for i, item in enumerate(items)
                 if js_truthy(call(cb, [item, float(i)]))])),
            "every": _native(lambda cb, *_: all(
                js_truthy(call(cb, [item, float(i)]))
                for i, item in enumerate(items))),
            "some": _native(lambda cb, *_: any(
                js_truthy(call(cb, [item, float(i)]))
                for i, item in enumerate(items))),
            "find": _native(lambda cb, *_: next(
                (item for i, item in enumerate(items)
                 if js_truthy(call(cb, [item, float(i)]))), UNDEFINED)),
            "findIndex": _native(lambda cb, *_: float(next(
                (i for i, item in enumerate(items)
                 if js_truthy(call(cb, [item, float(i)]))), -1))),
            "includes": _native(lambda target=UNDEFINED, *_: any(
                js_equals_strict(item, target) for item in items)),
            "indexOf": _native(lambda target=UNDEFINED, *_: float(next(
                (i for i, item in enumerate(items)
                 if js_equals_strict(item, target)), -1))),
            "join": _native(lambda sep=",", *_: js_str(sep).join(
                "" if item in (UNDEFINED, NULL) else js_str(item)
                for item in items)),
            "push": _native(lambda *args: (items.extend(args),
                                           float(len(items)))[1]),
            "pop": _native(lambda: items.pop() if items else UNDEFINED),
            "shift": _native(lambda: items.pop(0) if items else UNDEFINED),
            "unshift": _native(lambda *args: (items.__setitem__(
                slice(0, 0), list(args)), float(len(items)))[1]),
            "slice": _native(lambda start=0.0, end=None, *_: JSArray(
                items[_slice_index(start, len(items)):
                      _slice_index(end, len(items)) if end is not None
                      else len(items)])),
            "concat": _native(lambda *args: JSArray(
                items + [x for arg in args for x in (
                    arg.items if isinstance(arg, JSArray) else [arg])])),
            "flat": _native(lambda *_: JSArray(
                [x for item in items for x in (
                    item.items if isinstance(item, JSArray) else [item])])),
            "reverse": _native(lambda: (items.reverse(), arr)[1]),
            "sort": _native(lambda cmp=None: self._array_sort(arr, cmp)),
            "reduce": _native(lambda cb, *init: self._array_reduce(arr, cb, init)),
            "splice": _native(lambda start=0.0, count=None, *new: JSArray(
                _splice(items, start, count, list(new)))),
        }
        if prop in table:
            return table[prop]
        raise JSError(f"Array.{prop} is unsupported — extend tools/minijs.py")

    def _array_sort(self, arr, cmp):
        import functools as _ft

        if cmp in (None, UNDEFINED, NULL):
            arr.items.sort(key=js_str)
        else:
            arr.items.sort(key=_ft.cmp_to_key(
                lambda a, b: -1 if js_number(self.call_any(cmp, [a, b])) < 0
                else (1 if js_number(self.call_any(cmp, [a, b])) > 0 else 0)))
        return arr

    def _array_reduce(self, arr, callback, init):
        items = list(arr.items)
        if init:
            acc = init[0]
            start = 0
        else:
            if not items:
                raise JSException(_make_error("reduce of empty array"))
            acc = items[0]
            start = 1
        for i in range(start, len(items)):
            acc = self.call_any(callback, [acc, items[i], float(i)])
        return acc

    def _string_property(self, text: str, prop):
        if prop == "length":
            return float(len(text))
        try:
            index = int(prop)
            return text[index] if 0 <= index < len(text) else UNDEFINED
        except ValueError:
            pass
        table = {
            "slice": _native(lambda start=0.0, end=None, *_: text[
                _slice_index(start, len(text)):
                _slice_index(end, len(text)) if end is not None else len(text)]),
            "split": _native(lambda sep=UNDEFINED, *_: JSArray(
                list(text) if sep == "" else text.split(js_str(sep))
                if sep is not UNDEFINED else [text])),
            "replace": _native(lambda pat, repl, *_:
                               self._string_replace(text, pat, repl)),
            "includes": _native(lambda sub="", *_: js_str(sub) in text),
            "startsWith": _native(lambda sub="", *_: text.startswith(js_str(sub))),
            "endsWith": _native(lambda sub="", *_: text.endswith(js_str(sub))),
            "indexOf": _native(lambda sub="", *_: float(text.find(js_str(sub)))),
            "padStart": _native(lambda width=0.0, fill=" ", *_:
                                text.rjust(int(js_number(width)), js_str(fill))),
            "padEnd": _native(lambda width=0.0, fill=" ", *_:
                              text.ljust(int(js_number(width)), js_str(fill))),
            "toLowerCase": _native(lambda: text.lower()),
            "toUpperCase": _native(lambda: text.upper()),
            "trim": _native(lambda: text.strip()),
            "charCodeAt": _native(lambda i=0.0: float(ord(text[int(i)]))
                                  if 0 <= int(i) < len(text) else math.nan),
            "charAt": _native(lambda i=0.0: text[int(i)]
                              if 0 <= int(i) < len(text) else ""),
            "repeat": _native(lambda count=0.0: text * int(js_number(count))),
            "concat": _native(lambda *args: text + "".join(js_str(a) for a in args)),
            "localeCompare": _native(lambda other="":
                                     float((text > js_str(other)) -
                                           (text < js_str(other)))),
            "toString": _native(lambda: text),
            "match": _native(lambda pat: self._string_match(text, pat)),
        }
        if prop in table:
            return table[prop]
        raise JSError(f"String.{prop} is unsupported — extend tools/minijs.py")

    def _string_replace(self, text, pattern, replacement):
        def substitute(match):
            if callable(replacement) or isinstance(replacement, JSFunction):
                return js_str(self.call_any(replacement, [match.group(0)]))
            return js_str(replacement).replace("$&", match.group(0))

        if isinstance(pattern, JSRegex):
            count = 0 if pattern.global_ else 1
            return pattern.compiled.sub(substitute, text, count=count)
        needle = js_str(pattern)
        if callable(replacement) or isinstance(replacement, JSFunction):
            index = text.find(needle)
            if index < 0:
                return text
            replaced = js_str(self.call_any(replacement, [needle]))
            return text[:index] + replaced + text[index + len(needle):]
        return text.replace(needle, js_str(replacement), 1)

    def _string_match(self, text, pattern):
        if not isinstance(pattern, JSRegex):
            pattern = JSRegex(js_str(pattern), "")
        if pattern.global_:
            found = pattern.compiled.findall(text)
            return JSArray(list(found)) if found else NULL
        match = pattern.compiled.search(text)
        if match is None:
            return NULL
        return JSArray([match.group(0)] + [g if g is not None else UNDEFINED
                                           for g in match.groups()])

    def _number_property(self, number: float, prop):
        table = {
            "toFixed": _native(lambda digits=0.0:
                               f"{number:.{int(js_number(digits))}f}"),
            "toString": _native(lambda *_: js_str(number)),
            "toLocaleString": _native(lambda *_: f"{int(number):,}"
                                      if number == int(number) else js_str(number)),
        }
        if prop in table:
            return table[prop]
        raise JSError(f"Number.{prop} is unsupported")

    # -- globals ------------------------------------------------------------
    def _setup_globals(self):
        define = self.global_env.declare
        call = self.call_any

        console = JSObject({
            "log": _native(lambda *args: print("[js]", *map(js_str, args))),
            "warn": _native(lambda *args: print("[js:warn]", *map(js_str, args))),
            "error": _native(lambda *args: print("[js:err]", *map(js_str, args))),
        })
        define("console", console)

        define("JSON", JSObject({
            "stringify": _native(lambda value=UNDEFINED, *_:
                                 _json_stringify(value)),
            "parse": _native(lambda text="": _json_parse(js_str(text))),
        }))

        define("Math", JSObject({
            "max": _native(lambda *args: max((js_number(a) for a in args),
                                             default=-math.inf)),
            "min": _native(lambda *args: min((js_number(a) for a in args),
                                             default=math.inf)),
            "round": _native(lambda x=math.nan: float(math.floor(js_number(x) + 0.5))),
            "floor": _native(lambda x=math.nan: float(math.floor(js_number(x)))),
            "ceil": _native(lambda x=math.nan: float(math.ceil(js_number(x)))),
            "abs": _native(lambda x=math.nan: abs(js_number(x))),
            "random": _native(lambda: 0.42),    # deterministic for tests
            "trunc": _native(lambda x=math.nan: float(int(js_number(x)))),
            "pow": _native(lambda a=0.0, b=0.0: js_number(a) ** js_number(b)),
            "sqrt": _native(lambda x=0.0: math.sqrt(js_number(x))),
        }))

        object_ctor = _native(lambda value=UNDEFINED: value
                              if isinstance(value, JSObject) else JSObject())
        object_ctor.js_props = {
            "assign": _native(lambda target, *sources: _object_assign(
                target, sources)),
            "keys": _native(lambda obj=UNDEFINED: JSArray(
                list(_own_keys(obj)))),
            "values": _native(lambda obj=UNDEFINED: JSArray(
                [self.get_property(obj, key) for key in _own_keys(obj)])),
            "entries": _native(lambda obj=UNDEFINED: JSArray(
                [JSArray([key, self.get_property(obj, key)])
                 for key in _own_keys(obj)])),
            "fromEntries": _native(lambda pairs=UNDEFINED: JSObject(
                {js_str(p.items[0]): p.items[1]
                 for p in self._iterate(pairs)})),
        }
        define("Object", object_ctor)

        array_ctor = _native(lambda *args: JSArray(
            [UNDEFINED] * int(args[0]) if len(args) == 1 and
            isinstance(args[0], float) else list(args)))
        array_ctor.js_construct = array_ctor
        array_ctor.js_props = {
            "isArray": _native(lambda value=UNDEFINED: isinstance(value, JSArray)),
            "from": _native(lambda value=UNDEFINED, fn=None, *_: JSArray(
                [call(fn, [item, float(i)]) for i, item in
                 enumerate(self._iterate(value))] if fn not in (None, UNDEFINED)
                else self._iterate(value))),
        }
        define("Array", array_ctor)

        def date_ctor(*args):
            if not args:
                return JSDate.now()
            if len(args) == 1:
                arg = args[0]
                if isinstance(arg, JSDate):
                    return JSDate(arg.ms)
                if isinstance(arg, str):
                    return JSDate.parse(arg)
                return JSDate(js_number(arg))
            return JSDate.from_parts(*[js_number(a) for a in args])
        date_obj = _native(lambda *args: JSDate.now().toISOString())
        date_obj.js_construct = date_ctor
        date_obj.js_props = {"now": _native(lambda: JSDate.now().ms)}
        define("Date", date_obj)

        set_obj = _native(lambda *_: JSSet())
        set_obj.js_construct = lambda items=None, *_: JSSet(
            self._iterate(items) if items not in (None, UNDEFINED, NULL) else [])
        define("Set", set_obj)

        def promise_all(values=UNDEFINED, *_):
            out = []
            for item in self._iterate(values):
                promise = JSPromise.resolve(item)
                if promise.error is not None:
                    return promise
                out.append(promise.value)
            return JSPromise(value=JSArray(out))
        promise_obj = _native(lambda *_: UNDEFINED)
        promise_obj.js_props = {
            "all": _native(promise_all),
            "resolve": _native(lambda value=UNDEFINED: JSPromise.resolve(value)),
            "reject": _native(lambda value=UNDEFINED: JSPromise.reject(
                JSException(value))),
        }
        define("Promise", promise_obj)

        def error_ctor(message=UNDEFINED):
            return _make_error(js_str(message) if message is not UNDEFINED else "")
        error_obj = _native(error_ctor)
        error_obj.js_construct = error_ctor
        define("Error", error_obj)
        define("TypeError", error_obj)

        define("String", _native(lambda value="": js_str(value)))
        define("Number", _native(lambda value=0.0: js_number(value)))
        define("Boolean", _native(lambda value=UNDEFINED: js_truthy(value)))
        define("parseInt", _native(_parse_int))
        define("parseFloat", _native(_parse_float))
        define("isNaN", _native(lambda value=UNDEFINED:
                                math.isnan(js_number(value))))
        define("NaN", math.nan)
        define("Infinity", math.inf)
        define("encodeURIComponent", _native(_encode_uri_component))
        define("decodeURIComponent", _native(_decode_uri_component))

        # timers: recorded, never fired (the tests drive renders directly)
        self.timers: List[Tuple[Any, float]] = []
        define("setTimeout", _native(lambda fn=None, delay=0.0, *_:
                                     (self.timers.append((fn, delay)),
                                      float(len(self.timers)))[1]))
        define("setInterval", _native(lambda fn=None, delay=0.0, *_:
                                      (self.timers.append((fn, delay)),
                                       float(len(self.timers)))[1]))
        define("clearTimeout", _native(lambda *_: UNDEFINED))
        define("clearInterval", _native(lambda *_: UNDEFINED))


def _native(fn):
    """Wrap a python callable as a JS-callable native function; JS-level
    `undefined` padding for missing args is the python default values."""
    try:
        fn._js_native = True
    except AttributeError:
        pass    # bound methods reject attributes; the marker is advisory
    return fn


def _jsnum(value):
    if isinstance(value, (int,)) and not isinstance(value, bool):
        return float(value)
    return value


def _own_keys(obj):
    if isinstance(obj, JSObject):
        return list(obj.props.keys())
    if isinstance(obj, JSArray):
        return [js_str(float(i)) for i in range(len(obj.items))]
    keys = getattr(obj, "js_keys", None)
    if keys is not None:
        return list(keys())
    raise JSError(f"Object.keys on {type(obj).__name__} is unsupported")


def _object_assign(target, sources):
    for source in sources:
        if source in (UNDEFINED, NULL):
            continue
        if isinstance(source, JSObject):
            target.props.update(source.props)
        else:
            raise JSError("Object.assign source must be a plain object")
    return target


def _slice_index(value, length):
    if value is None or value is UNDEFINED:
        return length
    index = int(js_number(value))
    if index < 0:
        index += length
    return max(0, min(length, index))


def _splice(items, start, count, new_items):
    begin = _slice_index(start, len(items))
    removal = len(items) - begin if count in (None, UNDEFINED) \
        else max(0, int(js_number(count)))
    removed = items[begin:begin + removal]
    items[begin:begin + removal] = new_items
    return removed


def _parse_int(value="", base=10.0):
    text = js_str(value).strip()
    match = _re.match(r"[+-]?\d+", text)
    if not match:
        return math.nan
    return float(int(match.group(0), int(js_number(base)) or 10))


def _parse_float(value=""):
    match = _re.match(r"[+-]?\d*\.?\d+(?:[eE][+-]?\d+)?", js_str(value).strip())
    return float(match.group(0)) if match else math.nan


def _encode_uri_component(value=""):
    from urllib.parse import quote

    return quote(js_str(value), safe="!'()*-._~")


def _decode_uri_component(value=""):
    from urllib.parse import unquote

    return unquote(js_str(value))


def _json_stringify(value):
    def convert(v):
        if v is UNDEFINED:
            return None
        if v is NULL:
            return None
        if isinstance(v, (bool, str)):
            return v
        if isinstance(v, float):
            return int(v) if v == int(v) and abs(v) < 1e15 else v
        if isinstance(v, JSArray):
            return [convert(item) for item in v.items]
        if isinstance(v, JSObject):
            return {k: convert(val) for k, val in v.props.items()
                    if val is not UNDEFINED}
        if isinstance(v, JSSet):
            return {}
        if isinstance(v, JSDate):
            return v.toISOString()
        if callable(v):
            return None
        raise JSError(f"JSON.stringify: unsupported {type(v).__name__}")

    if value is UNDEFINED:
        return UNDEFINED
    return json.dumps(convert(value), separators=(",", ":"))


def _json_parse(text):
    try:
        doc = json.loads(text)
    except (ValueError, TypeError) as exc:
        raise JSException(_make_error(f"JSON.parse: {exc}", kind="SyntaxError"))

    def convert(v):
        if v is None:
            return NULL
        if isinstance(v, bool):
            return v
        if isinstance(v, (int, float)):
            return float(v)
        if isinstance(v, str):
            return v
        if isinstance(v, list):
            return JSArray([convert(item) for item in v])
        if isinstance(v, dict):
            return JSObject({k: convert(val) for k, val in v.items()})
        raise JSError("JSON.parse: unreachable")

    return convert(doc)


def _callee_name(node):
    if node[0] == "ident":
        return node[1]
    if node[0] == "member" and node[2][0] == "lit":
        return str(node[2][1])
    return "<expr>"


def _wants_this(func):
    return getattr(func, "_js_wants_this", False)


_ARITY_CACHE: Dict[Any, Optional[int]] = {}


def _trim_args(func, args):
    """JS ignores surplus arguments; python callables don't — trim to the
    callable's max positional arity (None = has *args)."""
    import inspect

    key = getattr(func, "__wrapped__", func)
    if key not in _ARITY_CACHE:
        try:
            params = inspect.signature(func).parameters.values()
        except (TypeError, ValueError):
            _ARITY_CACHE[key] = None
        else:
            if any(p.kind == p.VAR_POSITIONAL for p in params):
                _ARITY_CACHE[key] = None
            else:
                _ARITY_CACHE[key] = sum(
                    1 for p in params
                    if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD))
    arity = _ARITY_CACHE[key]
    if arity is None or len(args) <= arity:
        return args
    return args[:arity]


def _typeof(value):
    if value is UNDEFINED:
        return "undefined"
    if isinstance(value, bool):
        return "boolean"
    if isinstance(value, float):
        return "number"
    if isinstance(value, str):
        return "string"
    if isinstance(value, JSFunction) or callable(value):
        return "function"
    return "object"


def _to_primitive(value, hint="default"):
    if isinstance(value, JSDate):
        return value.toISOString() if hint == "default" else value.ms
    if isinstance(value, (JSObject, JSArray, JSSet)):
        return js_str(value)
    return value


def _to_int32(value):
    number = js_number(value)
    if math.isnan(number) or math.isinf(number):
        return 0
    return int(number) & 0xFFFFFFFF
