"""Benchmark: flagship transformer steps/sec/chip + telemetry poll p50.

Prints exactly ONE JSON line on stdout (driver contract); all diagnostics go
to stderr. Runs on whatever accelerator jax exposes (the driver provides one
real TPU chip; BASELINE.md records that the reference publishes no training
numbers, so ``vs_baseline`` is 1.0 by definition in round 1 and becomes the
round-over-round ratio once BENCH_r1.json exists).
"""
from __future__ import annotations

import json
import statistics
import subprocess
import sys
import time
from pathlib import Path


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_train(preset: str = "t2t-base") -> dict:
    import jax

    from tensorhive_tpu.models.transformer import PRESETS
    from tensorhive_tpu.train import TrainConfig, train_loop

    model_config = PRESETS[preset]
    on_tpu = jax.default_backend() == "tpu"
    train_config = TrainConfig(
        batch_size=16 if on_tpu else 2,
        seq_len=1024 if on_tpu else 128,
        warmup_steps=2,
        total_steps=100,
    )
    _log(f"backend={jax.default_backend()} devices={jax.devices()}")
    _log(f"model={preset} batch={train_config.batch_size} seq={train_config.seq_len}")
    steps = 12 if on_tpu else 4
    metrics = train_loop(model_config, train_config, mesh=None,
                         num_steps=steps, log_every=0)
    n_chips = max(1, len(jax.devices()))
    tokens_per_step = train_config.batch_size * train_config.seq_len
    return {
        "steps_per_sec_per_chip": metrics["steps_per_sec"] / n_chips,
        "tokens_per_sec_per_chip": metrics["steps_per_sec"] * tokens_per_step / n_chips,
        "step_time_ms": metrics["step_time_s"] * 1e3,
        "loss": metrics["loss"],
    }


def bench_telemetry_poll():
    """p50 latency (ms) of one native telemetry poll on this machine."""
    probe = Path(__file__).parent / "native" / "bin" / "tpuhive-probe"
    if not probe.exists():
        build = subprocess.run(["make", "-C", str(probe.parent.parent)],
                               capture_output=True, text=True)
        if build.returncode != 0 or not probe.exists():
            _log("native probe unavailable; skipping telemetry bench")
            return None
    samples = []
    for _ in range(21):
        started = time.perf_counter()
        subprocess.run([str(probe)], capture_output=True, timeout=30)
        samples.append((time.perf_counter() - started) * 1e3)
    return statistics.median(samples)


def main() -> None:
    train = bench_train()
    poll_p50_ms = bench_telemetry_poll()
    _log(f"train: {train}")
    _log(f"telemetry poll p50: {poll_p50_ms} ms")
    result = {
        "metric": "t2t_transformer steps/sec/chip",
        "value": round(train["steps_per_sec_per_chip"], 3),
        "unit": "steps/s/chip",
        "vs_baseline": 1.0,  # reference publishes no numbers (BASELINE.md)
        "tokens_per_sec_per_chip": round(train["tokens_per_sec_per_chip"], 1),
        "step_time_ms": round(train["step_time_ms"], 2),
        "telemetry_poll_p50_ms": round(poll_p50_ms, 2) if poll_p50_ms is not None else None,
        "loss": round(train["loss"], 4),
    }
    print(json.dumps(result, allow_nan=False))


if __name__ == "__main__":
    main()
