"""Benchmark: flagship transformer tokens/sec/chip + MFU + telemetry poll p50.

Prints exactly ONE JSON line on stdout (driver contract); all diagnostics go
to stderr. Sweeps a small grid of (batch, remat) configurations for the
headline t2t-base model and reports the best, plus a t2t-big data point, the
analytic MFU (model FLOPs / bf16 peak), and ``vs_baseline`` as the ratio
against round 1's recorded 74,788.5 tokens/s/chip (BENCH_r01.json) — the
reference itself publishes no training numbers (BASELINE.md), so the
round-over-round ratio is the honest comparison.
"""
from __future__ import annotations

import dataclasses
import json
import statistics
import subprocess
import sys
import time
from pathlib import Path

#: round-1 recorded throughput on this driver's hardware (BENCH_r01.json)
R01_TOKENS_PER_SEC_PER_CHIP = 74_788.5

#: v5e bf16 peak (TFLOP/s per chip); used only when the chip reports as v5e
PEAK_TFLOPS = {"v5 lite": 197.0, "v5": 459.0, "v4": 275.0, "v6 lite": 918.0}


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _peak_tflops() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for key, peak in PEAK_TFLOPS.items():
        if key in kind:
            return peak
    _log(f"WARNING: unknown device kind {kind!r}; assuming v5e peak "
         f"{PEAK_TFLOPS['v5 lite']} TFLOP/s for MFU")
    return PEAK_TFLOPS["v5 lite"]


def _run_config(preset: str, batch: int, seq_len: int, remat: bool,
                steps: int, remat_policy: str = "block") -> dict:
    import jax

    from tensorhive_tpu.models.transformer import PRESETS, train_flops_per_token
    from tensorhive_tpu.train import TrainConfig, train_loop

    model_config = dataclasses.replace(PRESETS[preset], remat=remat,
                                       remat_policy=remat_policy)
    train_config = TrainConfig(batch_size=batch, seq_len=seq_len,
                               warmup_steps=2, total_steps=100)
    # sync_every>1: enqueue steps back-to-back like a real training loop —
    # per-step device blocking would charge the host dispatch gap (~25% on
    # the tunneled chip) to every step
    metrics = train_loop(model_config, train_config, mesh=None,
                         num_steps=steps, log_every=0,
                         sync_every=max(1, steps // 3))
    if metrics["step_time_s"] * 1e3 < 5.0:
        # tunneled runtimes have been seen skipping device sync on the
        # first executable of a process; a sub-5ms "step" is physically
        # impossible for these shapes — measure again
        _log("  implausible step time, re-measuring")
        metrics = train_loop(model_config, train_config, mesh=None,
                             num_steps=steps, log_every=0,
                             sync_every=max(1, steps // 3))
    n_chips = max(1, len(jax.devices()))
    tokens_per_sec = batch * seq_len * metrics["steps_per_sec"] / n_chips
    # MFU by convention counts MODEL FLOPs (3x forward) regardless of remat
    # recompute — remat configs' hardware utilization is higher than their
    # MFU, which is the point of reporting MFU: it measures useful work
    flops_per_token = train_flops_per_token(model_config, seq_len, remat=False)
    mfu = tokens_per_sec * flops_per_token / (_peak_tflops() * 1e12)
    result = {
        "preset": preset,
        "batch": batch,
        "seq_len": seq_len,
        "remat": remat,
        "step_time_ms": round(metrics["step_time_s"] * 1e3, 2),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "steps_per_sec_per_chip": round(metrics["steps_per_sec"] / n_chips, 3),
        "mfu": round(mfu, 4),
        "loss": round(metrics["loss"], 4),
    }
    _log(f"  {result}")
    return result


def bench_train() -> dict:
    import jax

    on_tpu = jax.default_backend() == "tpu"
    _log(f"backend={jax.default_backend()} devices={jax.devices()}")
    if not on_tpu:
        _log("no TPU: single tiny config")
        best = _run_config("t2t-base", 2, 128, True, 4)
        return {"best": best, "sweep": [best], "big": None, "long_seq": None}

    # sweep the headline model (best-known config first so a driver timeout
    # mid-sweep still leaves the strongest point recorded)
    sweep = [
        # the headline config gets a deep measurement: longer sync windows
        # amortize the per-sync host gap toward pure device rate (measured:
        # 12/4 -> 181k, 24/8 -> 191k, 40/20 -> 197k tok/s on v5e)
        _run_config("t2t-base", 64, 1024, False, 45),
        _run_config("t2t-base", 32, 1024, False, 9),
        _run_config("t2t-base", 16, 1024, True, 9),
    ]
    best = max(sweep, key=lambda r: r["tokens_per_sec_per_chip"])
    big = _run_config("t2t-big", 32, 1024, False, 9)
    # long-context single-chip point: seq-4096 backward through the pallas
    # flash kernels + SELECTIVE remat ("mlp" policy: attention activations
    # stay saved so the backward never re-runs the VPU-bound flash forward —
    # measured 75.1k tok/s vs 63.7k full-block remat vs 33.9k in round 2).
    # The dense path cannot hold the [B,H,4096,4096] score matrix at any
    # batch size; logits at b8×s4096 still fit, so chunked CE is not engaged
    long_seq = _run_config("t2t-big", 8, 4096, True, 6, remat_policy="mlp")
    return {"best": best, "sweep": sweep, "big": big, "long_seq": long_seq}


def bench_telemetry_poll():
    """p50 latency (ms) of one native telemetry poll on this machine."""
    probe = (Path(__file__).parent / "tensorhive_tpu" / "native" / "bin"
             / "tpuhive-probe")
    if not probe.exists():
        build = subprocess.run(["make", "-C", str(probe.parent.parent)],
                               capture_output=True, text=True)
        if build.returncode != 0 or not probe.exists():
            _log("native probe unavailable; skipping telemetry bench")
            return None
    samples = []
    for _ in range(21):
        started = time.perf_counter()
        subprocess.run([str(probe)], capture_output=True, timeout=30)
        samples.append((time.perf_counter() - started) * 1e3)
    return statistics.median(samples)


def main() -> None:
    train = bench_train()
    poll_p50_ms = bench_telemetry_poll()
    best = train["best"]
    _log(f"best: {best}")
    _log(f"telemetry poll p50: {poll_p50_ms} ms")
    import jax

    on_tpu = jax.default_backend() == "tpu"
    result = {
        "metric": "t2t_transformer tokens/sec/chip",
        "value": best["tokens_per_sec_per_chip"],
        "unit": "tokens/s/chip",
        # R01 is a TPU v5e number: comparing a CPU smoke run against it
        # would report a spurious ~1000x regression, so off-TPU pins 1.0
        "vs_baseline": round(
            best["tokens_per_sec_per_chip"] / R01_TOKENS_PER_SEC_PER_CHIP, 3
        ) if on_tpu else 1.0,
        "mfu": best["mfu"],
        "steps_per_sec_per_chip": best["steps_per_sec_per_chip"],
        "step_time_ms": best["step_time_ms"],
        "best_config": {k: best[k] for k in ("preset", "batch", "seq_len", "remat")},
        "sweep": [
            {k: r[k] for k in ("batch", "remat", "tokens_per_sec_per_chip", "mfu")}
            for r in train["sweep"]
        ],
        "t2t_big": (
            {k: train["big"][k]
             for k in ("batch", "tokens_per_sec_per_chip", "mfu", "step_time_ms")}
            if train["big"] else None
        ),
        "long_seq_4096": (
            {k: train["long_seq"][k]
             for k in ("preset", "batch", "tokens_per_sec_per_chip", "mfu",
                       "step_time_ms")}
            if train.get("long_seq") else None
        ),
        "telemetry_poll_p50_ms": round(poll_p50_ms, 2) if poll_p50_ms is not None else None,
        "loss": best["loss"],
    }
    print(json.dumps(result, allow_nan=False))


if __name__ == "__main__":
    main()
