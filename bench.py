"""Benchmark: flagship transformer tokens/sec/chip + MFU + telemetry poll p50.

Prints exactly ONE JSON line on stdout (driver contract); all diagnostics go
to stderr. Sweeps a small grid of (batch, remat) configurations for the
headline t2t-base model and reports the best, plus a t2t-big data point, the
analytic MFU (model FLOPs / bf16 peak), and ``vs_baseline`` as the ratio
against round 1's recorded 74,788.5 tokens/s/chip (BENCH_r01.json) — the
reference itself publishes no training numbers (BASELINE.md), so the
round-over-round ratio is the honest comparison.
"""
from __future__ import annotations

import dataclasses
import json
import statistics
import subprocess
import sys
import time
from pathlib import Path

#: round-1 recorded throughput on this driver's hardware (BENCH_r01.json)
R01_TOKENS_PER_SEC_PER_CHIP = 74_788.5

#: v5e bf16 peak (TFLOP/s per chip); used only when the chip reports as v5e
PEAK_TFLOPS = {"v5 lite": 197.0, "v5": 459.0, "v4": 275.0, "v6 lite": 918.0}


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _peak_tflops() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for key, peak in PEAK_TFLOPS.items():
        if key in kind:
            return peak
    _log(f"WARNING: unknown device kind {kind!r}; assuming v5e peak "
         f"{PEAK_TFLOPS['v5 lite']} TFLOP/s for MFU")
    return PEAK_TFLOPS["v5 lite"]


def _run_config(preset: str, batch: int, seq_len: int, remat: bool,
                steps: int, remat_policy: str = "block",
                n_kv_heads=None) -> dict:
    import jax

    from tensorhive_tpu.models.transformer import PRESETS, train_flops_per_token
    from tensorhive_tpu.train import TrainConfig, train_loop

    model_config = dataclasses.replace(PRESETS[preset], remat=remat,
                                       remat_policy=remat_policy,
                                       n_kv_heads=n_kv_heads)
    train_config = TrainConfig(batch_size=batch, seq_len=seq_len,
                               warmup_steps=2, total_steps=100)
    # sync_every>1: enqueue steps back-to-back like a real training loop —
    # per-step device blocking would charge the host dispatch gap (~25% on
    # the tunneled chip) to every step
    metrics = train_loop(model_config, train_config, mesh=None,
                         num_steps=steps, log_every=0,
                         sync_every=max(1, steps // 3))
    if metrics["step_time_s"] * 1e3 < 5.0:
        # tunneled runtimes have been seen skipping device sync on the
        # first executable of a process; a sub-5ms "step" is physically
        # impossible for these shapes — measure again
        _log("  implausible step time, re-measuring")
        metrics = train_loop(model_config, train_config, mesh=None,
                             num_steps=steps, log_every=0,
                             sync_every=max(1, steps // 3))
    n_chips = max(1, len(jax.devices()))
    tokens_per_sec = batch * seq_len * metrics["steps_per_sec"] / n_chips
    # MFU by convention counts MODEL FLOPs (3x forward) regardless of remat
    # recompute — remat configs' hardware utilization is higher than their
    # MFU, which is the point of reporting MFU: it measures useful work
    flops_per_token = train_flops_per_token(model_config, seq_len, remat=False)
    mfu = tokens_per_sec * flops_per_token / (_peak_tflops() * 1e12)
    result = {
        "preset": preset,
        "batch": batch,
        "seq_len": seq_len,
        "remat": remat,
        "step_time_ms": round(metrics["step_time_s"] * 1e3, 2),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "steps_per_sec_per_chip": round(metrics["steps_per_sec"] / n_chips, 3),
        "mfu": round(mfu, 4),
        "loss": round(metrics["loss"], 4),
        "rejected_windows": int(metrics.get("rejected_windows", 0)),
    }
    if n_kv_heads is not None:
        result["n_kv_heads"] = n_kv_heads
    _log(f"  {result}")
    return result


def _try_config(*args, attempts: int = 3, **kwargs):
    """Run one sweep config with per-config fault isolation.

    BENCH_r03 lost the whole round's number to ONE transient
    ``remote_compile`` RPC failure mid-sweep (rc=1, parsed=null) — a bench
    whose output one flaky connection can destroy is not a bench. Transient
    runtime errors (JaxRuntimeError, dropped tunnel sockets) get the config
    re-run; a config that fails every attempt is recorded as None and the
    sweep carries on with whatever completed."""
    last = None
    for attempt in range(1, attempts + 1):
        try:
            return _run_config(*args, **kwargs)
        except Exception as exc:  # noqa: BLE001 — the JSON line must survive
            last = exc
            _log(f"  config {args} failed (attempt {attempt}/{attempts}): "
                 f"{type(exc).__name__}: {exc}")
    _log(f"  giving up on config {args}: {type(last).__name__}")
    return None


def bench_train() -> dict:
    import jax

    on_tpu = jax.default_backend() == "tpu"
    _log(f"backend={jax.default_backend()} devices={jax.devices()}")
    if not on_tpu:
        _log("no TPU: single tiny config")
        best = _try_config("t2t-base", 2, 128, True, 4)
        return {"best": best, "sweep": [best] if best else [],
                "big": None, "long_seq": None}

    # sweep the headline model (best-known config first so a driver timeout
    # mid-sweep still leaves the strongest point recorded)
    sweep = [r for r in (
        # the headline config gets a deep measurement: longer sync windows
        # amortize the per-sync host gap toward pure device rate (measured:
        # 12/4 -> 181k, 24/8 -> 191k, 40/20 -> 197k tok/s on v5e)
        _try_config("t2t-base", 64, 1024, False, 45),
        _try_config("t2t-base", 32, 1024, False, 9),
        _try_config("t2t-base", 16, 1024, True, 9),
    ) if r is not None]
    best = (max(sweep, key=lambda r: r["tokens_per_sec_per_chip"])
            if sweep else None)
    big = _try_config("t2t-big", 32, 1024, False, 9)
    # long-context single-chip point: seq-4096 backward through the pallas
    # flash kernels + SELECTIVE remat ("mlp" policy: attention activations
    # stay saved so the backward never re-runs the VPU-bound flash forward —
    # measured 75.1k tok/s vs 63.7k full-block remat vs 33.9k in round 2).
    # The dense path cannot hold the [B,H,4096,4096] score matrix at any
    # batch size; logits at b8×s4096 still fit, so chunked CE is not engaged
    long_seq = _try_config("t2t-big", 8, 4096, True, 6, remat_policy="mlp")
    # grouped-query point: same model with 4x fewer KV heads through the
    # native-GQA kernels (KV head h // group via the BlockSpec index maps,
    # no expanded copy) — records the kernel-level GQA win in the artifact
    gqa = _try_config("t2t-base", 64, 1024, False, 9, n_kv_heads=2)
    return {"best": best, "sweep": sweep, "big": big, "long_seq": long_seq,
            "gqa": gqa}


def bench_generate():
    """Serving-side numbers: batched-prefill tokens/s and steady-state
    decode tokens/s on t2t-base (the on-device lax.scan decode loop +
    one-pass prefill, models/decode.py). These existed since round 2/3 but
    never appeared in a BENCH artifact."""
    import jax
    import jax.numpy as jnp

    from tensorhive_tpu.models import decode
    from tensorhive_tpu.models.transformer import PRESETS, TransformerLM

    if jax.default_backend() == "tpu":
        preset = "t2t-base"
        batch, prompt_len, new_tokens = 8, 1024, 128
    else:
        # off-TPU smoke run: mirror bench_train's degradation — the full
        # t2t-base serving sweep on CPU takes minutes through the oracle
        preset = "tiny"
        batch, prompt_len, new_tokens = 2, 64, 8
    config = PRESETS[preset]
    total = prompt_len + new_tokens
    if config.max_seq_len < total:
        config = dataclasses.replace(config, max_seq_len=total)
    key = jax.random.PRNGKey(0)
    params = TransformerLM.init(key, config)
    prompt = jax.random.randint(key, (batch, prompt_len), 0,
                                config.vocab_size, dtype=jnp.int32)

    # prefill: one full-width trunk pass writes the prompt KV cache
    cache = decode.init_cache(config, batch, max_len=total)
    head = prompt[:, :prompt_len - 1]
    jax.block_until_ready(decode._prefill_cache(params, head, cache, config))
    reps = 3
    started = time.perf_counter()
    for _ in range(reps):
        filled = decode._prefill_cache(params, head, cache, config)
    jax.block_until_ready(filled)
    prefill_s = (time.perf_counter() - started) / reps
    prefill_tps = batch * (prompt_len - 1) / prefill_s

    # steady-state decode: the generation scan alone, cache pre-filled
    tokens = jnp.concatenate(
        [prompt, jnp.zeros((batch, new_tokens), jnp.int32)], axis=1)
    scan = lambda: decode._generate_on_device(  # noqa: E731
        params, tokens, filled, jax.random.PRNGKey(0), jnp.int32(prompt_len),
        jnp.float32(1.0), config=config, total=total, sampling=False,
        top_k=None, start=prompt_len - 1)
    scan().block_until_ready()
    started = time.perf_counter()
    for _ in range(reps):
        out = scan()
    out.block_until_ready()
    decode_s = (time.perf_counter() - started) / reps
    decode_tps = batch * new_tokens / decode_s
    result = {
        "preset": preset,
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "prefill_tokens_per_sec": round(prefill_tps, 1),
        "decode_tokens_per_sec": round(decode_tps, 1),
        "decode_ms_per_token": round(decode_s / new_tokens * 1e3, 3),
    }
    _log(f"  generate: {result}")
    return result


def bench_telemetry_poll():
    """p50 latency (ms) of one native telemetry poll on this machine."""
    probe = (Path(__file__).parent / "tensorhive_tpu" / "native" / "bin"
             / "tpuhive-probe")
    if not probe.exists():
        build = subprocess.run(["make", "-C", str(probe.parent.parent)],
                               capture_output=True, text=True)
        if build.returncode != 0 or not probe.exists():
            _log("native probe unavailable; skipping telemetry bench")
            return None
    samples = []
    for _ in range(21):
        started = time.perf_counter()
        subprocess.run([str(probe)], capture_output=True, timeout=30)
        samples.append((time.perf_counter() - started) * 1e3)
    return statistics.median(samples)


def main() -> None:
    """The driver records exactly one JSON line; every section below is
    fault-isolated so a late failure still emits whatever completed."""
    errors = []
    try:
        train = bench_train()
    except Exception as exc:  # noqa: BLE001
        _log(f"bench_train failed outright: {type(exc).__name__}: {exc}")
        errors.append(f"train: {type(exc).__name__}: {exc}")
        train = {"best": None, "sweep": [], "big": None, "long_seq": None}
    try:
        generate = bench_generate()
    except Exception as exc:  # noqa: BLE001
        _log(f"bench_generate failed: {type(exc).__name__}: {exc}")
        errors.append(f"generate: {type(exc).__name__}: {exc}")
        generate = None
    try:
        poll_p50_ms = bench_telemetry_poll()
    except Exception as exc:  # noqa: BLE001
        errors.append(f"telemetry: {type(exc).__name__}: {exc}")
        poll_p50_ms = None
    best = train["best"]
    _log(f"best: {best}")
    _log(f"telemetry poll p50: {poll_p50_ms} ms")
    try:
        import jax

        on_tpu = jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        on_tpu = False
    result = {
        "metric": "t2t_transformer tokens/sec/chip",
        "value": best["tokens_per_sec_per_chip"] if best else 0.0,
        "unit": "tokens/s/chip",
        # R01 is a TPU v5e number: comparing a CPU smoke run against it
        # would report a spurious ~1000x regression, so off-TPU pins 1.0;
        # an on-TPU sweep that produced NOTHING reports null, not fake parity
        "vs_baseline": (round(
            best["tokens_per_sec_per_chip"] / R01_TOKENS_PER_SEC_PER_CHIP, 3
        ) if best else None) if on_tpu else 1.0,
        "mfu": best["mfu"] if best else None,
        "steps_per_sec_per_chip": best["steps_per_sec_per_chip"] if best else None,
        "step_time_ms": best["step_time_ms"] if best else None,
        "best_config": (
            {k: best[k] for k in ("preset", "batch", "seq_len", "remat")}
            if best else None
        ),
        "sweep": [
            {k: r[k] for k in ("batch", "remat", "tokens_per_sec_per_chip", "mfu")}
            for r in train["sweep"]
        ],
        "t2t_big": (
            {k: train["big"][k]
             for k in ("batch", "tokens_per_sec_per_chip", "mfu", "step_time_ms")}
            if train["big"] else None
        ),
        "long_seq_4096": (
            {k: train["long_seq"][k]
             for k in ("preset", "batch", "tokens_per_sec_per_chip", "mfu",
                       "step_time_ms")}
            if train.get("long_seq") else None
        ),
        "gqa_kv2": (
            {k: train["gqa"][k]
             for k in ("batch", "n_kv_heads", "tokens_per_sec_per_chip",
                       "mfu", "step_time_ms")}
            if train.get("gqa") else None
        ),
        "generate": generate,
        "telemetry_poll_p50_ms": round(poll_p50_ms, 2) if poll_p50_ms is not None else None,
        "loss": best["loss"] if best else None,
    }
    if errors:
        result["errors"] = errors
    print(json.dumps(result, allow_nan=False))


if __name__ == "__main__":
    main()
